"""ray_tpu.tune — hyperparameter search on the ray_tpu runtime.

TPU-native equivalent of Ray Tune (ref: python/ray/tune/): Tuner.fit
(tuner.py:43, fit :312) drives a TuneController event loop
(execution/tune_controller.py:68) over actor-per-trial trainables with
PG-per-trial placement, basic variant generation (grid + random sampling),
and ASHA / median-stopping early termination (schedulers/).

    from ray_tpu import tune

    def trainable(config):
        for step in range(10):
            tune.report({"loss": config["lr"] * step})

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.loguniform(1e-4, 1e-1)},
        tune_config=tune.TuneConfig(num_samples=8, metric="loss", mode="min"),
    )
    results = tuner.fit()
    best = results.get_best_result()
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable

from ray_tpu.train.checkpoint import Checkpoint  # noqa: F401  (re-export)
from ray_tpu.tune.controller import (
    ERRORED,
    STOPPED,
    TERMINATED,
    Trial,
    TuneController,
)
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    Searcher,
    TPESearcher,
    choice,
    generate_variants,
    grid_search,
    loguniform,
    quniform,
    randint,
    uniform,
)
from ray_tpu.tune.session import get_checkpoint, report

__all__ = [
    "ASHAScheduler",
    "Searcher",
    "TPESearcher",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "Result",
    "ResultGrid",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "report",
    "uniform",
]


@dataclasses.dataclass
class TuneConfig:
    """(ref: tune/tune_config.py TuneConfig)"""

    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    scheduler: object | None = None
    search_alg: object | None = None  # a search.Searcher (e.g. TPESearcher)
    callbacks: list | None = None  # air.LoggerCallback instances
    seed: int | None = None
    max_failures_per_trial: int = 0


class Result:
    def __init__(self, trial: Trial):
        self.trial_id = trial.trial_id
        self.config = trial.config
        self.metrics = trial.metrics
        self.metrics_history = trial.history
        self.checkpoint = (
            Checkpoint(trial.checkpoint_path) if trial.checkpoint_path else None
        )
        self.error = trial.error
        self.status = trial.status

    def __repr__(self):
        return f"Result({self.trial_id}, status={self.status}, metrics={self.metrics})"


class ResultGrid:
    """(ref: tune/result_grid.py ResultGrid)"""

    def __init__(self, results: list[Result], metric: str | None, mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> list[Result]:
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None, mode: str | None = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric given to get_best_result or TuneConfig")
        scored = [r for r in self._results if metric in (r.metrics or {})]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def get_dataframe(self):
        rows = [
            {"trial_id": r.trial_id, **{f"config/{k}": v for k, v in r.config.items()},
             **(r.metrics or {})}
            for r in self._results
        ]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows


class Tuner:
    """(ref: tune/tuner.py:43; restore/resume is the experiment_state.json
    written by the controller)"""

    def __init__(self, trainable: Callable | object, *, param_space: dict | None = None,
                 tune_config: TuneConfig | None = None, run_config=None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        trainable, resources = _as_trainable(self.trainable)
        if tc.search_alg is not None:
            # suggest-driven: the controller creates trials on demand so
            # later suggestions observe earlier results (TPE semantics)
            variants = []
        else:
            variants = generate_variants(self.param_space, tc.num_samples,
                                         tc.seed)
            if not variants:
                variants = [{}]
        storage = None
        if self.run_config is not None:
            storage = getattr(self.run_config, "storage_path", None)
            name = getattr(self.run_config, "name", None)
        else:
            name = None
        if storage is None:
            import uuid as _uuid

            storage = f"/tmp/ray_tpu/tune/{name or 'exp'}_{_uuid.uuid4().hex[:8]}"
        controller = TuneController(
            trainable,
            variants,
            scheduler=tc.scheduler,
            metric=tc.metric,
            mode=tc.mode,
            max_concurrent_trials=tc.max_concurrent_trials,
            resources_per_trial=resources,
            storage_path=storage,
            max_failures_per_trial=tc.max_failures_per_trial,
            trials=getattr(self, "_restored_trials", None),
            searcher=tc.search_alg,
            num_samples=tc.num_samples,
            callbacks=tc.callbacks,
        )
        trials = controller.run()
        return ResultGrid([Result(t) for t in trials], tc.metric, tc.mode)

    @classmethod
    def restore(cls, path: str, trainable: Callable | object,
                tune_config: TuneConfig | None = None) -> "Tuner":
        """Resume an interrupted experiment from its storage_path (ref:
        tune/tuner.py Tuner.restore + execution/experiment_state.py): the
        controller's periodic snapshots rebuild the trial table; finished
        trials keep their results, unfinished ones run again from their
        last checkpoint. Call .fit() on the returned Tuner to continue."""
        import types

        trials = TuneController.load_experiment_state(path)
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=types.SimpleNamespace(storage_path=path,
                                                    name=None))
        tuner._restored_trials = trials
        return tuner


def _as_trainable(obj) -> tuple[Callable, dict]:
    """Accept a plain function(config) or a JaxTrainer (Tune-over-Train,
    ref: BaseTrainer.fit wrapping itself as a Trainable, base_trainer.py:808)."""
    from ray_tpu.train.trainer import JaxTrainer

    if isinstance(obj, JaxTrainer):
        trainer = obj

        def trainable(config: dict):
            import dataclasses as _dc
            import os as _os

            from ray_tpu import tune
            from ray_tpu.train.trainer import JaxTrainer as _JT
            from ray_tpu.tune.session import get_session

            merged = dict(trainer.train_loop_config or {})
            merged.update(config.get("train_loop_config", config))
            # per-trial run name + storage subdir: concurrent trials must
            # not share checkpoint dirs or collective group namespaces
            trial_id = get_session().trial_id
            run_cfg = _dc.replace(trainer.run_config)
            run_cfg.name = f"{run_cfg.name or 'tune'}_{trial_id}"
            if run_cfg.storage_path:
                run_cfg.storage_path = _os.path.join(run_cfg.storage_path, trial_id)
            t = _JT(
                trainer.train_loop,
                train_loop_config=merged,
                scaling_config=trainer.scaling,
                run_config=run_cfg,
            )
            result = t.fit()
            if result.error is not None:
                raise result.error
            tune.report(result.metrics, checkpoint=result.checkpoint)
            return result.metrics

        # the trial actor itself is light; its nested train workers carry
        # the real resources
        return trainable, {"CPU": 0.5}
    return obj, {"CPU": 1.0}

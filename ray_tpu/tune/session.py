"""Per-trial session: tune.report / tune.get_checkpoint inside trainables.

TPU-native equivalent of the reference's trial-side session (ref:
python/ray/tune/trainable/function_trainable.py _StatusReporter,
tune/trainable/session.py). One session per trial-actor process; the
trainable thread enqueues reports that the driver-side controller drains
via TrialActor.poll().
"""
from __future__ import annotations

import queue

from ray_tpu.train.checkpoint import Checkpoint

_session = None


class TuneSession:
    def __init__(self, trial_id: str, config: dict, checkpoint: Checkpoint | None):
        self.trial_id = trial_id
        self.config = config
        self.checkpoint = checkpoint
        self.outbox: queue.Queue = queue.Queue()
        self.iteration = 0
        self.stop_requested = False


def init_session(trial_id: str, config: dict, checkpoint: Checkpoint | None) -> TuneSession:
    global _session
    _session = TuneSession(trial_id, config, checkpoint)
    return _session


def get_session() -> TuneSession:
    if _session is None:
        raise RuntimeError("tune.report called outside a Tune trial")
    return _session


class TrialStopped(Exception):
    """Raised inside the trainable when the scheduler stopped the trial."""


def report(metrics: dict, *, checkpoint: Checkpoint | None = None) -> None:
    """Report metrics (and optionally a checkpoint) to the controller
    (ref: tune session.report). training_iteration auto-increments if the
    trainable doesn't set it. Raises TrialStopped if the scheduler has
    decided to early-stop this trial."""
    s = get_session()
    s.iteration += 1
    metrics = dict(metrics)
    metrics.setdefault("training_iteration", s.iteration)
    s.outbox.put((metrics, checkpoint))
    if s.stop_requested:
        raise TrialStopped()


def get_checkpoint() -> Checkpoint | None:
    return get_session().checkpoint

"""TuneController: drives trials as actors, applies scheduler decisions.

TPU-native equivalent of the reference TuneController (ref:
python/ray/tune/execution/tune_controller.py:68 — event loop step :666,
actor management _schedule_trial_actor :964) with PG-per-trial resources
(tune/execution/placement_groups.py PlacementGroupFactory). Trials run as
TrialActor actors; the controller polls their report outboxes, feeds the
scheduler, early-stops losers, and retries failed trials up to
max_failures_per_trial.
"""
from __future__ import annotations

import logging
import json
import os
import time
import traceback
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import CONTINUE, EXPLOIT, STOP

_log = logging.getLogger(__name__)

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
STOPPED = "STOPPED"  # early-stopped by the scheduler
ERRORED = "ERRORED"


class TrialActor:
    """Actor hosting one trial's trainable function."""

    def __init__(self, trial_id: str, storage_path: str):
        from ray_tpu.tune import session as tune_session

        self.trial_id = trial_id
        self.storage_path = storage_path
        self._done = False
        self._error: str | None = None
        self._session = None
        self._tune_session_mod = tune_session

    def run(self, trainable: Callable, config: dict,
            checkpoint_path: str | None = None, start_iteration: int = 0):
        """Blocking trainable execution (executor thread; poll() stays
        servable on the actor loop — same split as TrainWorker.run)."""
        from ray_tpu.tune.session import TrialStopped, init_session

        ckpt = Checkpoint.from_directory(checkpoint_path) if checkpoint_path else None
        self._session = init_session(self.trial_id, config, ckpt)
        # resumed trials continue their iteration count so schedulers don't
        # re-record rungs the trial already passed
        self._session.iteration = start_iteration
        try:
            out = trainable(config)
            return {"ok": True, "result": out}
        except TrialStopped:
            return {"ok": True, "stopped": True}
        except Exception as e:  # noqa: BLE001
            self._error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            return {"ok": False, "error": self._error}
        finally:
            self._done = True

    def poll(self):
        # read _done BEFORE draining: a report enqueued between the drain
        # and the done-check would otherwise be lost on the final poll
        done = self._done
        out = []
        if self._session is not None:
            while not self._session.outbox.empty():
                metrics, ckpt = self._session.outbox.get_nowait()
                out.append((metrics, ckpt.path if ckpt else None))
        return {"reports": out, "done": done, "error": self._error}

    def request_stop(self):
        if self._session is not None:
            self._session.stop_requested = True
        return True


@dataclass
class Trial:
    trial_id: str
    config: dict
    status: str = PENDING
    metrics: dict = field(default_factory=dict)
    history: list = field(default_factory=list)
    checkpoint_path: str | None = None
    error: str | None = None
    failures: int = 0
    actor: Any = None
    run_ref: Any = None
    pg: Any = None


class TuneController:
    def __init__(self, trainable: Callable, variants: list[dict], *,
                 scheduler=None, metric: str | None = None, mode: str = "max",
                 max_concurrent_trials: int | None = None,
                 resources_per_trial: dict | None = None,
                 storage_path: str, max_failures_per_trial: int = 0,
                 trials: list[Trial] | None = None,
                 searcher=None, num_samples: int | None = None,
                 callbacks: list | None = None):
        self.trainable = trainable
        self.scheduler = scheduler or sched_mod.FIFOScheduler()
        # suggest-driven search (ref: tune_controller + SearchGenerator):
        # trials are appended on demand up to num_samples, so each
        # suggest() observes every completed trial so far
        self.searcher = searcher
        self.num_samples = num_samples or 1
        self._searcher_exhausted = False  # suggest() returned None
        # driver-side logger callbacks (ref: tune/logger LoggerCallback;
        # air/integrations wandb+mlflow ride this hook)
        self.callbacks = list(callbacks or [])
        for cb in self.callbacks:
            cb.setup(os.path.basename(storage_path))
        self.metric = metric
        self.mode = mode
        self.max_concurrent = max_concurrent_trials or 4
        self.resources = dict(resources_per_trial or {"CPU": 1.0})
        self.storage_path = storage_path
        self.max_failures = max_failures_per_trial
        # restored experiments pass their rebuilt trial table directly
        self.trials = trials if trials is not None else [
            Trial(trial_id=f"trial_{i:05d}_{uuid.uuid4().hex[:6]}", config=cfg)
            for i, cfg in enumerate(variants)
        ]
        os.makedirs(storage_path, exist_ok=True)

    # -------------------------------------------------------------- run loop
    def run(self) -> list[Trial]:
        """Event loop (ref: tune_controller.py step :666)."""
        last_state_write = 0.0
        while True:
            self._maybe_suggest()
            self._start_pending()
            # periodic state snapshots make a killed driver resumable via
            # Tuner.restore (ref: experiment_state.py periodic sync)
            if time.monotonic() - last_state_write > 1.0:
                self._write_experiment_state()
                last_state_write = time.monotonic()
            running = [t for t in self.trials if t.status == RUNNING]
            if not running:
                done_count = 0 if (self.searcher is None
                                   or self._searcher_exhausted) \
                    else self.num_samples
                if (len(self.trials) >= done_count
                        and all(t.status in (TERMINATED, STOPPED, ERRORED)
                                for t in self.trials)):
                    break
                time.sleep(0.02)
                continue
            self._poll_running(running)
            time.sleep(0.02)
        self._write_experiment_state()
        for cb in self.callbacks:
            try:
                cb.on_experiment_end()
            except Exception:
                _log.debug("callback on_experiment_end failed", exc_info=True)
        return self.trials

    def _maybe_suggest(self):
        if self.searcher is None:
            return
        active = sum(1 for t in self.trials
                     if t.status in (PENDING, RUNNING))
        while (not self._searcher_exhausted
               and len(self.trials) < self.num_samples
               and active < self.max_concurrent):
            tid = f"trial_{len(self.trials):05d}_{uuid.uuid4().hex[:6]}"
            cfg = self.searcher.suggest(tid)
            if cfg is None:
                # the searcher is done producing configs: run() must
                # terminate after the existing trials finish, not wait
                # for num_samples that will never come
                self._searcher_exhausted = True
                break
            self.trials.append(Trial(trial_id=tid, config=cfg))
            active += 1

    def _start_pending(self):
        running = sum(1 for t in self.trials if t.status == RUNNING)
        for trial in self.trials:
            if running >= self.max_concurrent:
                break
            if trial.status != PENDING:
                continue
            try:
                self._launch(trial)
                running += 1
            except Exception as e:  # cluster can't host it right now
                trial.error = str(e)
                trial.status = ERRORED

    def _launch(self, trial: Trial):
        # PG-per-trial so multi-resource trials get gang placement
        # (ref: tune/execution/placement_groups.py)
        trial.pg = ray_tpu.placement_group([dict(self.resources)], strategy="PACK")
        if not trial.pg.ready(timeout=60):
            raise RuntimeError(
                f"trial {trial.trial_id}: placement group {self.resources} "
                "not placeable on this cluster"
            )
        cpus = self.resources.get("CPU", 1.0)
        other = {k: v for k, v in self.resources.items() if k != "CPU"}
        trial.actor = (
            ray_tpu.remote(TrialActor)
            .options(
                num_cpus=cpus,
                resources=other,
                placement_group=trial.pg,
                placement_group_bundle_index=0,
                max_concurrency=2,  # poll() while run() occupies the executor
            )
            .remote(trial.trial_id, self.storage_path)
        )
        trial.run_ref = trial.actor.run.remote(
            self.trainable, trial.config, trial.checkpoint_path, len(trial.history)
        )
        trial.status = RUNNING
        for cb in self.callbacks:
            try:
                cb.on_trial_start(trial.trial_id, trial.config)
            except Exception:
                _log.debug("callback on_trial_start failed", exc_info=True)

    def _poll_running(self, running: list[Trial]):
        # submit every poll before retrieving any so trials answer
        # concurrently; retrieval stays per-ref because one dead actor
        # must not sink the whole batch
        refs = [t.actor.poll.remote() for t in running]
        polls = []
        for ref in refs:
            try:
                polls.append(ray_tpu.get(ref, timeout=30))  # raylint: disable=RT002
            except Exception:
                polls.append(None)  # actor died
        for trial, poll in zip(running, polls):
            if poll is None:
                self._on_trial_failed(trial, "trial actor died")
                continue
            for metrics, ckpt_path in poll["reports"]:
                trial.metrics = metrics
                trial.history.append(metrics)
                for cb in self.callbacks:
                    try:
                        cb.on_trial_result(trial.trial_id, metrics)
                    except Exception:
                        _log.debug("callback on_trial_result failed",
                                   exc_info=True)
                if ckpt_path:
                    trial.checkpoint_path = ckpt_path
                decision = self.scheduler.on_result(trial.trial_id, metrics)
                if decision == STOP:
                    self._stop_trial(trial)
                    break
                if decision == EXPLOIT:
                    self._exploit_trial(trial)
                    break
            if trial.status == RUNNING and poll["done"]:
                self._finish_trial(trial, poll)

    def _finish_trial(self, trial: Trial, poll: dict):
        try:
            r = ray_tpu.get(trial.run_ref, timeout=30)
        except Exception as e:
            self._on_trial_failed(trial, str(e))
            return
        if not r.get("ok"):
            self._on_trial_failed(trial, r.get("error", "unknown"))
            return
        trial.status = STOPPED if r.get("stopped") else TERMINATED
        self.scheduler.on_trial_complete(trial.trial_id, trial.metrics or None)
        if self.searcher is not None:
            self.searcher.on_trial_complete(trial.trial_id,
                                            trial.metrics or None)
        for cb in self.callbacks:
            try:
                cb.on_trial_complete(trial.trial_id, trial.metrics or None)
            except Exception:
                _log.debug("callback on_trial_complete failed", exc_info=True)
        self._teardown(trial)

    def _stop_trial(self, trial: Trial):
        """Scheduler early-stop: ask the trainable to raise at next report."""
        try:
            ray_tpu.get(trial.actor.request_stop.remote(), timeout=10)
        except Exception:  # raylint: disable=RT012 — actor may already be dead; teardown below reaps it
            pass
        trial.status = STOPPED
        self.scheduler.on_trial_complete(trial.trial_id, trial.metrics or None)
        if self.searcher is not None:
            self.searcher.on_trial_complete(trial.trial_id,
                                            trial.metrics or None)
        for cb in self.callbacks:
            try:
                cb.on_trial_complete(trial.trial_id, trial.metrics or None)
            except Exception:
                _log.debug("callback on_trial_complete failed", exc_info=True)
        self._teardown(trial)

    def _exploit_trial(self, trial: Trial):
        """PBT exploit+explore (ref: tune/schedulers/pbt.py): clone a
        top-quantile trial's checkpoint, mutate its config, restart this
        trial from the clone."""
        donor_id = self.scheduler.pick_donor(exclude=trial.trial_id)
        donor = next((t for t in self.trials if t.trial_id == donor_id), None)
        if donor is None or donor.checkpoint_path is None:
            return  # nothing to clone yet: keep training
        try:
            ray_tpu.get(trial.actor.request_stop.remote(), timeout=10)
        except Exception:  # raylint: disable=RT012 — actor may already be dead; teardown below reaps it
            pass
        self._teardown(trial)
        trial.config = self.scheduler.explore(dict(donor.config))
        trial.checkpoint_path = donor.checkpoint_path
        trial.status = PENDING  # relaunch resumes from the donor's state
        self.scheduler.num_exploits += 1

    def _on_trial_failed(self, trial: Trial, error: str):
        trial.failures += 1
        self._teardown(trial)
        if trial.failures <= self.max_failures:
            trial.status = PENDING  # retry (resumes from its last checkpoint)
        else:
            trial.status = ERRORED
            trial.error = error
            self.scheduler.on_trial_complete(trial.trial_id, None)
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.trial_id, None)
            for cb in self.callbacks:
                try:
                    cb.on_trial_complete(trial.trial_id, None)
                except Exception:
                    _log.debug("callback on_trial_complete failed",
                               exc_info=True)

    def _teardown(self, trial: Trial):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:  # raylint: disable=RT012 — teardown: actor may already be dead
                pass
            trial.actor = None
        if trial.pg is not None:
            try:
                ray_tpu.remove_placement_group(trial.pg)
            except Exception:  # raylint: disable=RT012 — teardown: PG may already be gone
                pass
            trial.pg = None

    # ------------------------------------------------------------ experiment
    def _write_experiment_state(self):
        """Persist the trial table for resumability + analysis
        (ref: tune/execution/experiment_state.py). JSON for humans; a
        pickle sidecar carries full-fidelity configs/history for
        Tuner.restore."""
        state = [
            {
                "trial_id": t.trial_id,
                "config": _jsonable(t.config),
                "status": t.status,
                "metrics": _jsonable(t.metrics),
                "checkpoint_path": t.checkpoint_path,
                "error": t.error,
            }
            for t in self.trials
        ]
        with open(os.path.join(self.storage_path, "experiment_state.json"), "w") as f:
            json.dump(state, f, indent=2, default=str)
        import pickle

        full = [
            {
                "trial_id": t.trial_id,
                "config": t.config,
                "status": t.status,
                "metrics": t.metrics,
                "history": t.history,
                "checkpoint_path": t.checkpoint_path,
                "error": t.error,
            }
            for t in self.trials
        ]
        tmp = os.path.join(self.storage_path, "experiment_state.pkl.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(full, f)
        os.replace(tmp, os.path.join(self.storage_path, "experiment_state.pkl"))

    @staticmethod
    def load_experiment_state(storage_path: str) -> list[Trial]:
        """Rebuild the trial table from a (possibly killed) experiment's
        snapshots. Unfinished trials come back PENDING and resume from
        their last checkpoint; finished ones keep their results."""
        import pickle

        path = os.path.join(storage_path, "experiment_state.pkl")
        with open(path, "rb") as f:
            rows = pickle.load(f)
        trials = []
        for r in rows:
            t = Trial(trial_id=r["trial_id"], config=r["config"])
            t.metrics = r.get("metrics") or {}
            t.history = r.get("history") or []
            t.checkpoint_path = r.get("checkpoint_path")
            status = r.get("status")
            if status in (TERMINATED, STOPPED):
                t.status = status
                t.error = r.get("error")
            else:  # PENDING / RUNNING / ERRORED at kill time: run it again
                t.status = PENDING
            trials.append(t)
        return trials


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)

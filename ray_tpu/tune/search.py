"""Search-space primitives + basic variant generation.

TPU-native equivalent of the reference search surface (ref:
python/ray/tune/search/sample.py uniform/loguniform/choice/randint,
search/basic_variant.py BasicVariantGenerator, search/grid_search).
Grid dimensions expand to a cross-product; sampling dimensions draw
num_samples independent variants — matching the reference's semantics
where num_samples multiplies the grid.
"""
from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class _Sampler:
    fn: Callable[[random.Random], Any]
    repr_name: str

    def sample(self, rng: random.Random):
        return self.fn(rng)

    def __repr__(self):
        return self.repr_name


def uniform(low: float, high: float) -> _Sampler:
    return _Sampler(lambda rng: rng.uniform(low, high), f"uniform({low}, {high})")


def loguniform(low: float, high: float) -> _Sampler:
    import math

    lo, hi = math.log(low), math.log(high)
    return _Sampler(lambda rng: math.exp(rng.uniform(lo, hi)), f"loguniform({low}, {high})")


def randint(low: int, high: int) -> _Sampler:
    return _Sampler(lambda rng: rng.randrange(low, high), f"randint({low}, {high})")


def choice(options: list) -> _Sampler:
    opts = list(options)
    return _Sampler(lambda rng: rng.choice(opts), f"choice({opts})")


def quniform(low: float, high: float, q: float) -> _Sampler:
    return _Sampler(
        lambda rng: round(rng.uniform(low, high) / q) * q, f"quniform({low}, {high}, {q})"
    )


class grid_search(dict):
    """Marker: expand this dimension as a grid (ref: tune grid_search)."""

    def __init__(self, values: list):
        super().__init__(grid_search=list(values))

    @property
    def values(self):
        return self["grid_search"]


def generate_variants(param_space: dict, num_samples: int = 1,
                      seed: int | None = None) -> list[dict]:
    """Expand a param space into concrete trial configs
    (ref: basic_variant.py BasicVariantGenerator)."""
    rng = random.Random(seed)
    grid_keys: list[tuple[tuple, list]] = []
    _collect_grids(param_space, (), grid_keys)
    grid_axes = [vals for _, vals in grid_keys]
    combos = list(itertools.product(*grid_axes)) if grid_axes else [()]
    variants = []
    for _ in range(num_samples):
        for combo in combos:
            cfg = _materialize(param_space, rng)
            for (path, _), value in zip(grid_keys, combo):
                _set_path(cfg, path, value)
            variants.append(cfg)
    return variants


def _collect_grids(node, path, out):
    if isinstance(node, grid_search):
        out.append((path, node.values))
    elif isinstance(node, dict):
        for k, v in node.items():
            _collect_grids(v, path + (k,), out)


def _materialize(node, rng):
    if isinstance(node, grid_search):
        return None  # placeholder; overwritten by _set_path
    if isinstance(node, _Sampler):
        return node.sample(rng)
    if isinstance(node, dict):
        return {k: _materialize(v, rng) for k, v in node.items()}
    return node


def _set_path(cfg: dict, path: tuple, value):
    node = cfg
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value

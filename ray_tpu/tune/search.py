"""Search-space primitives + basic variant generation.

TPU-native equivalent of the reference search surface (ref:
python/ray/tune/search/sample.py uniform/loguniform/choice/randint,
search/basic_variant.py BasicVariantGenerator, search/grid_search).
Grid dimensions expand to a cross-product; sampling dimensions draw
num_samples independent variants — matching the reference's semantics
where num_samples multiplies the grid.
"""
from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class _Sampler:
    fn: Callable[[random.Random], Any]
    repr_name: str
    kind: str = "custom"  # uniform/loguniform/randint/choice/quniform
    meta: dict | None = None  # kind-specific params (TPE models need them)

    def sample(self, rng: random.Random):
        return self.fn(rng)

    def __repr__(self):
        return self.repr_name


def uniform(low: float, high: float) -> _Sampler:
    return _Sampler(lambda rng: rng.uniform(low, high),
                    f"uniform({low}, {high})",
                    kind="uniform", meta={"low": low, "high": high})


def loguniform(low: float, high: float) -> _Sampler:
    import math

    lo, hi = math.log(low), math.log(high)
    return _Sampler(lambda rng: math.exp(rng.uniform(lo, hi)),
                    f"loguniform({low}, {high})",
                    kind="loguniform", meta={"low": low, "high": high})


def randint(low: int, high: int) -> _Sampler:
    return _Sampler(lambda rng: rng.randrange(low, high),
                    f"randint({low}, {high})",
                    kind="randint", meta={"low": low, "high": high})


def choice(options: list) -> _Sampler:
    opts = list(options)
    return _Sampler(lambda rng: rng.choice(opts), f"choice({opts})",
                    kind="choice", meta={"options": opts})


def quniform(low: float, high: float, q: float) -> _Sampler:
    return _Sampler(
        lambda rng: round(rng.uniform(low, high) / q) * q,
        f"quniform({low}, {high}, {q})",
        kind="quniform", meta={"low": low, "high": high, "q": q})


class grid_search(dict):
    """Marker: expand this dimension as a grid (ref: tune grid_search)."""

    def __init__(self, values: list):
        super().__init__(grid_search=list(values))

    @property
    def values(self):
        return self["grid_search"]


def generate_variants(param_space: dict, num_samples: int = 1,
                      seed: int | None = None) -> list[dict]:
    """Expand a param space into concrete trial configs
    (ref: basic_variant.py BasicVariantGenerator)."""
    rng = random.Random(seed)
    grid_keys: list[tuple[tuple, list]] = []
    _collect_grids(param_space, (), grid_keys)
    grid_axes = [vals for _, vals in grid_keys]
    combos = list(itertools.product(*grid_axes)) if grid_axes else [()]
    variants = []
    for _ in range(num_samples):
        for combo in combos:
            cfg = _materialize(param_space, rng)
            for (path, _), value in zip(grid_keys, combo):
                _set_path(cfg, path, value)
            variants.append(cfg)
    return variants


def _collect_grids(node, path, out):
    if isinstance(node, grid_search):
        out.append((path, node.values))
    elif isinstance(node, dict):
        for k, v in node.items():
            _collect_grids(v, path + (k,), out)


def _materialize(node, rng):
    if isinstance(node, grid_search):
        return None  # placeholder; overwritten by _set_path
    if isinstance(node, _Sampler):
        return node.sample(rng)
    if isinstance(node, dict):
        return {k: _materialize(v, rng) for k, v in node.items()}
    return node


def _set_path(cfg: dict, path: tuple, value):
    node = cfg
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value


# ------------------------------------------------------------------ searchers
class Searcher:
    """Sequential suggest/observe interface (ref: tune/search/searcher.py
    Searcher.suggest / on_trial_complete). Plugged into TuneController via
    TuneConfig(search_alg=...): trials are created on demand instead of
    expanded upfront, so later suggestions see earlier results."""

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, metrics: dict | None) -> None:
        pass


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator (the role of the
    reference's pluggable HyperOpt/Optuna searchers, ref:
    tune/search/hyperopt/hyperopt_search.py — implemented here directly:
    split observations into good/bad by the gamma quantile, model each
    dimension with a Parzen (Gaussian-kernel) density per split, and pick
    the candidate maximizing l(x)/g(x)).

    Supports uniform / loguniform / quniform / randint / choice
    dimensions (nested dicts fine); unknown sampler kinds fall back to
    random draws for that dimension.
    """

    def __init__(self, space: dict, metric: str, mode: str = "max", *,
                 n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int | None = None):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.space = space
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._dims: list[tuple[tuple, _Sampler]] = []
        _collect_samplers(space, (), self._dims)
        self._live: dict[str, dict] = {}   # trial_id -> flat values
        self._obs: list[tuple[dict, float]] = []  # (flat values, score)

    # ------------------------------------------------------------- suggest
    def suggest(self, trial_id: str) -> dict:
        import math

        flat: dict[tuple, Any] = {}
        use_model = len(self._obs) >= self.n_initial
        if use_model:
            good, bad = self._split()
        for path, dim in self._dims:
            if not use_model or dim.kind not in (
                    "uniform", "loguniform", "quniform", "randint", "choice"):
                flat[path] = dim.sample(self.rng)
                continue
            gvals = [o[path] for o, _ in good if path in o]
            bvals = [o[path] for o, _ in bad if path in o]
            if dim.kind == "choice":
                flat[path] = self._suggest_categorical(
                    dim.meta["options"], gvals, bvals)
            elif dim.kind == "randint":
                # bounded numeric, NOT categorical: materializing
                # range(lo, hi) would blow up on wide integer spaces
                # (seeds, buffer sizes) — model as a Parzen over the
                # continuous range and round
                lo, hi = dim.meta["low"], dim.meta["high"]
                x = self._suggest_parzen(
                    [float(v) for v in gvals], [float(v) for v in bvals],
                    float(lo), float(hi - 1))
                flat[path] = int(min(max(round(x), lo), hi - 1))
            else:
                lo, hi = dim.meta["low"], dim.meta["high"]
                logspace = dim.kind == "loguniform"
                xform = math.log if logspace else (lambda v: v)
                inv = math.exp if logspace else (lambda v: v)
                x = self._suggest_parzen(
                    [xform(v) for v in gvals], [xform(v) for v in bvals],
                    xform(lo), xform(hi))
                x = inv(x)
                if dim.kind == "quniform":
                    q = dim.meta["q"]
                    x = round(x / q) * q
                flat[path] = min(max(x, lo), hi)
        self._live[trial_id] = dict(flat)
        cfg = _materialize(self.space, self.rng)
        for path, v in flat.items():
            _set_path(cfg, path, v)
        return cfg

    def on_trial_complete(self, trial_id: str, metrics: dict | None) -> None:
        flat = self._live.pop(trial_id, None)
        if flat is None or not metrics or self.metric not in metrics:
            return
        score = float(metrics[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((flat, score))

    # ------------------------------------------------------------ internals
    def _split(self):
        ranked = sorted(self._obs, key=lambda o: -o[1])
        n_good = max(1, int(round(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_categorical(self, options: list, gvals, bvals):
        # add-one smoothed category weights: p_good / p_bad odds
        def weights(vals):
            counts = {id_: 1.0 for id_ in range(len(options))}
            index = {repr(o): i for i, o in enumerate(options)}
            for v in vals:
                i = index.get(repr(v))
                if i is not None:
                    counts[i] += 1.0
            total = sum(counts.values())
            return [counts[i] / total for i in range(len(options))]

        wg, wb = weights(gvals), weights(bvals)
        odds = [g / b for g, b in zip(wg, wb)]
        # sample candidates from the good distribution, keep the best odds
        best, best_odds = None, -1.0
        for _ in range(self.n_candidates):
            i = self.rng.choices(range(len(options)), weights=wg)[0]
            if odds[i] > best_odds:
                best, best_odds = i, odds[i]
        return options[best]

    def _suggest_parzen(self, gvals, bvals, lo, hi):
        import math

        span = max(hi - lo, 1e-12)

        def kde(vals):
            # Parzen mixture: one Gaussian per observation + a uniform
            # prior component over the range (keeps densities positive)
            if not vals:
                return [(0.5 * (lo + hi), span)], 1.0 / max(len(vals) + 1, 1)
            bw = max(span * (len(vals) ** -0.2) * 0.5, 1e-9 * span)
            return [(v, bw) for v in vals], 1.0 / (len(vals) + 1)

        def density(mix, prior_w, x):
            comps, _ = mix, None
            p = prior_w / span  # uniform prior component
            if comps:
                w = (1.0 - prior_w) / len(comps)
                for mu, bw in comps:
                    z = (x - mu) / bw
                    p += w * math.exp(-0.5 * z * z) / (bw * 2.5066282746310002)
            return p

        gmix, gprior = kde(gvals)
        bmix, bprior = kde(bvals)
        best_x, best_score = None, -1.0
        for _ in range(self.n_candidates):
            # draw from the good mixture (or the prior when empty)
            if gvals and self.rng.random() > gprior:
                mu, bw = self.rng.choice(gmix)
                x = self.rng.gauss(mu, bw)
            else:
                x = self.rng.uniform(lo, hi)
            x = min(max(x, lo), hi)
            score = density(gmix, gprior, x) / max(
                density(bmix, bprior, x), 1e-12)
            if score > best_score:
                best_x, best_score = x, score
        return best_x


def _collect_samplers(node, path, out):
    if isinstance(node, _Sampler):
        out.append((path, node))
    elif isinstance(node, dict) and not isinstance(node, grid_search):
        for k, v in node.items():
            _collect_samplers(v, path + (k,), out)

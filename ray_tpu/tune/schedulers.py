"""Trial schedulers: early stopping of unpromising trials.

TPU-native equivalents of the reference schedulers (ref:
python/ray/tune/schedulers/async_hyperband.py AsyncHyperBandScheduler —
the ASHA algorithm, median_stopping_rule.py, trial_scheduler.py
FIFOScheduler). Decisions are made on each reported result:
CONTINUE or STOP.
"""
from __future__ import annotations

import collections

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping (ref: trial_scheduler.py FIFOScheduler)."""

    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        pass


class ASHAScheduler:
    """Asynchronous Successive Halving (ref: async_hyperband.py:19 — the
    ASHA paper's algorithm): rungs at grace_period * reduction_factor^k;
    a trial reaching a rung continues only if its metric is in the top
    1/reduction_factor of results recorded at that rung."""

    def __init__(self, metric: str, mode: str = "max", time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4, max_t: int = 100):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung milestone -> list of recorded metric values
        self.rungs: dict[int, list[float]] = collections.defaultdict(list)
        # rung milestone -> trial_ids already recorded there (trials report
        # at arbitrary strides; each crosses a rung at most once)
        self._recorded: dict[int, set[str]] = collections.defaultdict(set)
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones

    def _val(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        t = int(result[self.time_attr])
        v = self._val(result)
        decision = CONTINUE
        # evaluate every rung the trial has crossed (t >= milestone, not
        # equality — trials may report in strides; matches the reference's
        # largest-milestone-<=-t behavior)
        for milestone in self.milestones:
            if t >= milestone and trial_id not in self._recorded[milestone]:
                self._recorded[milestone].add(trial_id)
                recorded = self.rungs[milestone]
                recorded.append(v)
                # continue only in the top 1/rf at this rung: cutoff is the
                # (1 - 1/rf) percentile of recorded values (matches the
                # reference _Bracket.cutoff, async_hyperband.py)
                import numpy as np

                cutoff = float(np.nanpercentile(recorded, (1 - 1 / self.rf) * 100))
                if v < cutoff:
                    decision = STOP
        return decision

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        pass


class MedianStoppingRule:
    """Stop a trial whose running-average metric falls below the median of
    other trials' averages at the same step (ref:
    median_stopping_rule.py:18)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: dict[str, list[float]] = collections.defaultdict(list)

    def _val(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        t = int(result[self.time_attr])
        self._history[trial_id].append(self._val(result))
        if t < self.grace_period:
            return CONTINUE
        others = [
            sum(h) / len(h)
            for tid, h in self._history.items()
            if tid != trial_id and h
        ]
        if len(others) < self.min_samples:
            return CONTINUE
        import statistics

        mine = self._history[trial_id]
        my_avg = sum(mine) / len(mine)
        return STOP if my_avg < statistics.median(others) else CONTINUE

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        self._history.pop(trial_id, None)


EXPLOIT = "EXPLOIT"


class PopulationBasedTraining:
    """PBT (ref: tune/schedulers/pbt.py PopulationBasedTraining): at every
    ``perturbation_interval`` (in ``time_attr`` units), a trial in the
    bottom quantile EXPLOITS — the controller clones a top-quantile
    trial's checkpoint and config — and EXPLORES: each mutable
    hyperparameter is resampled (prob ``resample_probability``) or
    perturbed by x1.2 / x0.8. The controller executes the clone+restart;
    this object only decides and mutates."""

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25, seed: int = 0):
        assert mode in ("max", "min")
        assert 0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        import numpy as _np
        import random as _random

        self._rng = _np.random.default_rng(seed)
        self._pyrng = _random.Random(seed)  # tune samplers take random.Random
        self.scores: dict[str, float] = {}  # trial_id -> latest score
        self._last_perturb: dict[str, int] = {}
        self.num_exploits = 0

    def _val(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def _quantiles(self):
        ranked = sorted(self.scores, key=self.scores.get)
        n = max(1, int(len(ranked) * self.quantile))
        if len(ranked) < 2 * n:
            return [], []
        return ranked[:n], ranked[-n:]  # (bottom, top)

    def on_result(self, trial_id: str, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        self.scores[trial_id] = self._val(result)
        t = int(result[self.time_attr])
        if t - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        bottom, top = self._quantiles()
        if trial_id in bottom and top:
            return EXPLOIT
        return CONTINUE

    def pick_donor(self, exclude: str) -> str | None:
        """A random top-quantile trial to clone from."""
        _, top = self._quantiles()
        top = [t for t in top if t != exclude]
        if not top:
            return None
        return top[int(self._rng.integers(0, len(top)))]

    def explore(self, config: dict) -> dict:
        """Mutate the donor's config (ref: pbt.py _explore)."""
        out = dict(config)
        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or key not in out:
                if callable(spec):
                    out[key] = spec()
                elif hasattr(spec, "sample"):
                    out[key] = spec.sample(self._pyrng)
                else:  # explicit list of values
                    out[key] = spec[int(self._rng.integers(0, len(spec)))]
            else:
                factor = 1.2 if self._rng.random() < 0.5 else 0.8
                if isinstance(out[key], (int, float)):
                    out[key] = type(out[key])(out[key] * factor)
        return out

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        # keep the score: a finished top-quantile trial remains a valid
        # donor (its checkpoint exists) for still-running stragglers
        pass

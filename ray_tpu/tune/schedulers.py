"""Trial schedulers: early stopping of unpromising trials.

TPU-native equivalents of the reference schedulers (ref:
python/ray/tune/schedulers/async_hyperband.py AsyncHyperBandScheduler —
the ASHA algorithm, median_stopping_rule.py, trial_scheduler.py
FIFOScheduler). Decisions are made on each reported result:
CONTINUE or STOP.
"""
from __future__ import annotations

import collections

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """No early stopping (ref: trial_scheduler.py FIFOScheduler)."""

    def on_result(self, trial_id: str, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        pass


class ASHAScheduler:
    """Asynchronous Successive Halving (ref: async_hyperband.py:19 — the
    ASHA paper's algorithm): rungs at grace_period * reduction_factor^k;
    a trial reaching a rung continues only if its metric is in the top
    1/reduction_factor of results recorded at that rung."""

    def __init__(self, metric: str, mode: str = "max", time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4, max_t: int = 100):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung milestone -> list of recorded metric values
        self.rungs: dict[int, list[float]] = collections.defaultdict(list)
        # rung milestone -> trial_ids already recorded there (trials report
        # at arbitrary strides; each crosses a rung at most once)
        self._recorded: dict[int, set[str]] = collections.defaultdict(set)
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(t)
            t *= reduction_factor
        self.milestones = milestones

    def _val(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        t = int(result[self.time_attr])
        v = self._val(result)
        decision = CONTINUE
        # evaluate every rung the trial has crossed (t >= milestone, not
        # equality — trials may report in strides; matches the reference's
        # largest-milestone-<=-t behavior)
        for milestone in self.milestones:
            if t >= milestone and trial_id not in self._recorded[milestone]:
                self._recorded[milestone].add(trial_id)
                recorded = self.rungs[milestone]
                recorded.append(v)
                # continue only in the top 1/rf at this rung: cutoff is the
                # (1 - 1/rf) percentile of recorded values (matches the
                # reference _Bracket.cutoff, async_hyperband.py)
                import numpy as np

                cutoff = float(np.nanpercentile(recorded, (1 - 1 / self.rf) * 100))
                if v < cutoff:
                    decision = STOP
        return decision

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        pass


class MedianStoppingRule:
    """Stop a trial whose running-average metric falls below the median of
    other trials' averages at the same step (ref:
    median_stopping_rule.py:18)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: dict[str, list[float]] = collections.defaultdict(list)

    def _val(self, result: dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        t = int(result[self.time_attr])
        self._history[trial_id].append(self._val(result))
        if t < self.grace_period:
            return CONTINUE
        others = [
            sum(h) / len(h)
            for tid, h in self._history.items()
            if tid != trial_id and h
        ]
        if len(others) < self.min_samples:
            return CONTINUE
        import statistics

        mine = self._history[trial_id]
        my_avg = sum(mine) / len(mine)
        return STOP if my_avg < statistics.median(others) else CONTINUE

    def on_trial_complete(self, trial_id: str, result: dict | None) -> None:
        self._history.pop(trial_id, None)

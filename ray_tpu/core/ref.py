"""ObjectRef and user-facing error types.

Equivalent of the reference's ObjectRef + error taxonomy
(ref: python/ray/_raylet.pyx ObjectRef, python/ray/exceptions.py).
An ObjectRef carries its owner's RPC address — ownership-based object
resolution (ref: ownership_object_directory.cc): whoever created the object
serves its metadata and small values.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.utils.ids import ActorID, ObjectID, TaskID


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray.get (ref: RayTaskError)."""

    def __init__(self, message: str, cause_repr: str = "", traceback_str: str = ""):
        super().__init__(message)
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str

    def __str__(self):
        base = super().__str__()
        if self.traceback_str:
            return f"{base}\n\n--- remote traceback ---\n{self.traceback_str}"
        return base


class ActorError(RayTpuError):
    """The actor died before/while executing this call (ref: RayActorError)."""


class ActorUnavailableError(ActorError):
    pass


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray.cancel (ref: TaskCancelledError)."""


class WorkerCrashedError(TaskError):
    def __init__(self, message="worker process died while executing the task"):
        super().__init__(message)


class ObjectLostError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ConfigurationError(RayTpuError):
    """The cluster cannot run this task as configured (e.g. a cpp task with
    no RT_CPP_WORKER binary). Never transient: retrying cannot succeed, so
    the lease-failure breaker fails pending tasks on it immediately."""


class SchedulingError(ConfigurationError):
    """No node can satisfy the task's scheduling strategy (hard node
    affinity to a dead node, hard labels nothing matches). Fails fast
    like ConfigurationError rather than parking forever (deliberate
    deviation from the reference's wait-for-a-matching-node)."""


class ObjectRef:
    """Future-like handle to a (possibly pending) remote object."""

    __slots__ = ("id", "owner_address", "_core", "_borrowed", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: tuple[str, int] | None = None,
                 _core=None, _borrowed: bool = False):
        self.id = object_id
        self.owner_address = owner_address
        self._core = _core  # owner: enables GC; borrower: enables unborrow
        self._borrowed = _borrowed

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self) -> TaskID:
        return self.id.task_id()

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __reduce__(self):
        # Borrower protocol (ref: reference_count.h:72): the sender notes
        # the shipment (owner defers freeing while refs are in flight) and
        # the receiver registers itself as a borrower at unpickle time.
        core = self._core
        if core is None:
            from ray_tpu.core import api

            core = api._core
        if core is not None:
            try:
                # payload-embedded ref: the recipient rehydrates it as an
                # ObjectRef and registers a borrow — the owner holds the
                # object on the long no-borrow leash until that lands
                core.note_ref_shipped(self.id, self, expect_borrow=True)
            except Exception:  # raylint: disable=RT012 — __reduce__ during teardown must never raise
                pass
        return (_rebuild_borrowed_ref, (self.id, self.owner_address))

    def __del__(self):
        core = self._core
        if core is not None:
            try:
                if self._borrowed:
                    core.on_borrowed_ref_deleted(self.id, self.owner_address)
                else:
                    core.on_owned_ref_deleted(self.id)
            except Exception:  # raylint: disable=RT012 — __del__ may run at interpreter exit
                pass

    # await support inside async actors
    def __await__(self):
        from ray_tpu.core import api

        async def _get():
            # completion fast lane: an already-resolved ref (ready
            # memory-store entry, sealed local shm object) returns
            # without entering the async get machinery at all
            core = self._core or api._core
            if core is not None:
                hit = core.get_local_prepass([self]).get(self.id)
                if hit is not None:
                    if hit[0] == "e":
                        raise hit[1]
                    return hit[1]
            return await api._async_get(self)

        return _get().__await__()


def _rebuild_borrowed_ref(object_id: ObjectID, owner_address):
    """Unpickle hook: register this process as a borrower with the owner
    (ref: borrower registration in reference_count.cc). On the owner's own
    process the ref resolves back to an owned handle."""
    from ray_tpu.core import api

    core = api._core
    if core is None:
        return ObjectRef(object_id, owner_address)
    if owner_address is not None and tuple(owner_address) == core.address:
        core.on_owned_ref_created(object_id)
        return ObjectRef(object_id, owner_address, _core=core)
    core.on_borrowed_ref_created(object_id, owner_address)
    return ObjectRef(object_id, owner_address, _core=core, _borrowed=True)


class ObjectRefGenerator:
    """Iterator over the ObjectRefs a streaming task yields one by one
    (ref: python/ray/_raylet.pyx:282 ObjectRefGenerator; items are reported
    back to the owner as they are produced, core_worker.proto:498
    ReportGeneratorItemReturns). Works as a sync iterator on driver
    threads and an async iterator inside async actors."""

    def __init__(self, task_id: TaskID, core):
        self._task_id = task_id
        self._core = core

    # ------------------------------------------------------------------ sync
    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        ref = self._core.gen_next_sync(self._task_id)
        if ref is None:
            raise StopIteration
        return ref

    # ----------------------------------------------------------------- async
    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        ref = await self._core.gen_next(self._task_id)
        if ref is None:
            raise StopAsyncIteration
        return ref

    def completed(self) -> bool:
        return self._core.gen_completed(self._task_id)

    def __del__(self):
        core = self._core
        if core is not None:
            try:
                core.gen_release(self._task_id)
            except Exception:  # raylint: disable=RT012 — __del__ may run at interpreter exit
                pass


class ActorHandle:
    """Typed proxy for remote actor method calls; see core_client.submit_actor_task."""

    def __init__(self, actor_id: ActorID, core=None, method_names: tuple = (),
                 options: dict | None = None):
        self._actor_id = actor_id
        self._core = core
        self._method_names = method_names
        self._options = options or {}
        # owner-local handle refcount (core_client autokill): only
        # handles of unnamed actors the creating driver enrolled count;
        # at zero the core kills the actor so its lease returns
        self._counted = False
        if core is not None:
            try:
                self._counted = core.note_actor_handle_created(actor_id)
            except AttributeError:
                pass

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        num_returns = self._options.get("method_num_returns", {}).get(name)
        m = ActorMethod(self, name, num_returns=num_returns)
        # cache on the instance: the next ``handle.method`` hits plain
        # attribute lookup and skips both __getattr__ and the ActorMethod
        # rebuild — the actor-call analogue of the submit template. NOT
        # serialized (__reduce__ rebuilds from ids alone).
        self.__dict__[name] = m
        return m

    def __reduce__(self):
        if self._counted:
            try:
                # a shipped handle may outlive every local one: the
                # actor is permanently exempt from autokill
                self._core.note_actor_handle_shipped(self._actor_id)
            except Exception:  # raylint: disable=RT012 — __reduce__ during teardown must never raise
                pass
        return (_rebuild_actor_handle, (self._actor_id, self._method_names, self._options))

    def __del__(self):
        if self._counted:
            try:
                self._core.note_actor_handle_dropped(self._actor_id)
            except Exception:  # raylint: disable=RT012 — __del__ may run at interpreter exit
                pass

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"


class ActorMethod:
    def __init__(self, handle: ActorHandle, name: str, num_returns: int | None = None,
                 concurrency_group: str | None = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group
        # frozen per-(handle, method) submission template
        # (core_client.ActorCallTemplate): method-key bytes + options
        # eligibility + lane binding resolved once at the first call —
        # the actor twin of PR 2's SubmitTemplate. ActorMethods are
        # cached on the handle, so the template survives across calls.
        self._ftmpl = None

    def __getstate__(self):
        # the template pins the driver's CoreClient and lane: never ship
        # it with a method handle (it rebuilds wherever the method lands)
        state = self.__dict__.copy()
        state["_ftmpl"] = None
        return state

    def options(self, num_returns: int | None = None,
                concurrency_group: str | None = None, **kw):
        return ActorMethod(
            self._handle, self._name,
            self._num_returns if num_returns is None else num_returns,
            concurrency_group or self._concurrency_group)

    def remote(self, *args, **kwargs) -> Any:
        core = self._handle._core
        if core is None:
            from ray_tpu.core import api

            # backfill a deserialized handle once: later calls (and later
            # methods of the same handle) skip the lookup
            core = self._handle._core = api.get_core()
        tmpl = self._ftmpl
        if tmpl is None or tmpl.core is not core:
            tmpl = self._ftmpl = core.actor_call_template(
                self._handle.actor_id, self._name,
                self._num_returns or 1, self._concurrency_group)
        return core.submit_actor_task(
            self._handle, self._name, args, kwargs,
            num_returns=self._num_returns or 1,
            concurrency_group=self._concurrency_group,
            _tmpl=tmpl,
        )

    def bind(self, *args) -> Any:
        """Author a compiled-graph node for this method
        (ref: dag/dag_node.py bind API)."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args)


def _rebuild_actor_handle(actor_id, method_names, options):
    return ActorHandle(actor_id, core=None, method_names=method_names, options=options)

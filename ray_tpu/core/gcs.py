"""Global Control Service: cluster metadata authority.

TPU-native equivalent of the reference GCS server (ref:
src/ray/gcs/gcs_server/gcs_server.h:90) — node registry + health checks
(gcs_health_check_manager.h:45), actor manager + scheduler
(gcs_actor_manager.h:329, gcs_actor_scheduler.h), placement groups with
two-phase bundle reservation (gcs_placement_group_mgr.h:232,
LeaseStatusTracker gcs_placement_group_scheduler.h:133), internal KV
(gcs_kv_manager.h:34), long-poll-free push pubsub (src/ray/pubsub/
publisher.h:300), and the function table the workers fetch code from.

Runs as its own process (``python -m ray_tpu.core.gcs``); all state is
in-memory (the reference's default) — a Redis-style persistence backend can
slot behind the table dicts for GCS fault tolerance in a later iteration.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Any

from ray_tpu.config import get_config
from ray_tpu.core import policy
from ray_tpu.devtools import chaos
from ray_tpu.utils import aio, rpc
from ray_tpu.utils.ids import ActorID, JobID, NodeID, PlacementGroupID

log = logging.getLogger(__name__)

# actor lifecycle states (ref: gcs.proto ActorTableData.ActorState)
PENDING = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


@dataclass
class NodeInfo:
    node_id: NodeID
    address: tuple[str, int]  # raylet rpc address
    store_name: str
    resources_total: dict[str, float]
    resources_available: dict[str, float]
    labels: dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    queued_leases: int = 0  # demand signal (autoscaler)
    pid: int = 0
    # sender-assigned monotonic version of this node's resource view
    # (ref: ray_syncer.h:83 versioned messages — stale deliveries are
    # dropped by version comparison, both at the GCS and at receivers)
    view_version: int = 0

    def view(self) -> dict:
        return {
            "node_id": self.node_id,
            "address": self.address,
            "store_name": self.store_name,
            "resources_total": dict(self.resources_total),
            "resources_available": dict(self.resources_available),
            "labels": dict(self.labels),
            "alive": self.alive,
            "queued_leases": self.queued_leases,
            "pid": self.pid,
            "view_version": self.view_version,
        }


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: str | None
    state: str
    spec: dict  # creation spec (class bytes ref, args, resources, options)
    address: tuple[str, int] | None = None
    node_id: NodeID | None = None
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str | None = None

    def view(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "name": self.name,
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id,
            "num_restarts": self.num_restarts,
            "death_cause": self.death_cause,
            # driver-side method metadata: handles from get_actor() must
            # honor @method(num_returns=...) like creation handles do
            "method_num_returns": self.spec.get("method_num_returns") or {},
        }


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    bundles: list[dict[str, float]]
    strategy: str
    # state machine: PENDING -> CREATED -> (RESCHEDULING <-> CREATED) -> REMOVED
    # (ref: gcs_placement_group_mgr.h:232 PlacementGroupState — RESCHEDULING
    # is the reconciled-desired-state leg: bundles on dead nodes are
    # re-placed instead of the PG being abandoned)
    state: str
    # one slot per bundle; None = not (or no longer) placed. Fully
    # populated exactly when state == CREATED.
    bundle_nodes: list[NodeID | None] = field(default_factory=list)
    reschedule_cause: str | None = None
    reschedules: int = 0

    def __setstate__(self, state):
        # WAL/snapshot records from before the FT fields existed restore
        # without them: default in place so recovery never AttributeErrors
        self.__dict__.update(state)
        self.__dict__.setdefault("reschedule_cause", None)
        self.__dict__.setdefault("reschedules", 0)

    def lost_indices(self, alive: "set[NodeID]") -> list[int]:
        return [i for i, nid in enumerate(self.bundle_nodes)
                if nid is None or nid not in alive]


class BundleTxn:
    """Tracker for one two-phase reservation round over a subset of a
    PG's bundles (the LeaseStatusTracker role, ref:
    gcs_placement_group_scheduler.h:133). Prepare and commit each fan
    out in PARALLEL over the GCS's pooled raylet connections; per-bundle
    outcomes land in ``prepared`` / ``committed`` / ``failed`` so the
    caller can repair exactly what broke instead of raising out of the
    RPC with reservations stranded on every prepared node."""

    def __init__(self, gcs: "GcsServer", pg: PlacementGroupInfo,
                 placement: dict[int, NodeInfo]):
        self.gcs = gcs
        self.pg = pg
        self.placement = placement  # bundle index -> target node
        self.prepared: dict[int, NodeInfo] = {}
        self.committed: dict[int, NodeInfo] = {}
        self.failed: dict[int, NodeInfo] = {}

    async def _phase_one(self, point: str, method: str, index: int,
                         node: NodeInfo) -> bool:
        if chaos.ENABLED:
            # "gcs.pg_prepare" / "gcs.pg_commit" fault points: `error`
            # raises here and is absorbed as THAT bundle's phase failure
            # (repair re-places it); `drop` refuses the reservation;
            # `delay` time.sleeps the whole GCS loop — the
            # frozen-coordinator shape, same semantics as the other
            # GCS-side points (keep delay_ms small in plans)
            act = chaos.point(point, pg=self.pg.pg_id.hex()[:12],
                              bundle=index, node=node.node_id.hex()[:12])
            if act is not None and act.kind == "drop":
                return False
        # no call/phase timeout on purpose: wait_for task-wraps its
        # awaitable (~70µs per Task on a small host — it dominated the
        # create path). The unhang guarantee comes from the pool
        # instead: _mark_node_dead drops the node's pooled connection,
        # which fails every in-flight call here with ConnectionLost.
        r = await self.gcs._node_call(
            node, method,
            {"pg_id": self.pg.pg_id, "bundle_index": index,
             "resources": self.pg.bundles[index]})
        return bool(r and r.get("ok"))

    async def _phase(self, point: str, method: str,
                     items: list[tuple[int, NodeInfo]],
                     into: dict[int, NodeInfo]) -> bool:
        """Run one 2PC phase over ``items``. Bundles GROUP per node and
        each node's group rides ONE batched RPC (prepare_bundles /
        commit_bundles — one ledger pass raylet-side) since protocol
        2.0; distinct nodes still fan out in parallel (the RTTs
        overlap). A single bundle awaits directly — the gather/Task
        wrapping costs ~70µs a phase on a small host, most of a
        1-bundle PG's create path."""
        if len(items) == 1:
            index, node = items[0]
            try:
                ok = await self._phase_one(point, method, index, node)
            except Exception:
                ok = False
            (into if ok else self.failed)[index] = node
            return not self.failed
        groups: dict = {}
        for index, node in items:
            groups.setdefault(node.node_id, []).append((index, node))
        coros = []
        for group in groups.values():
            if len(group) == 1:
                coros.append(self._phase_single(point, method, group[0],
                                                into))
            else:
                coros.append(self._phase_group(point, method, group, into))
        if len(coros) == 1:
            await coros[0]
        else:
            await asyncio.gather(*coros)
        return not self.failed

    async def _phase_single(self, point: str, method: str, item, into):
        index, node = item
        try:
            ok = await self._phase_one(point, method, index, node)
        except Exception:
            ok = False
        (into if ok else self.failed)[index] = node

    async def _phase_group(self, point: str, method: str,
                           group: list, into) -> None:
        """One node's multi-bundle phase leg: per-bundle chaos verdicts
        first (an injected fault fails exactly that bundle, the rest
        still ride the batch), then ONE batched raylet RPC."""
        node = group[0][1]
        send: list[int] = []
        for index, _ in group:
            if chaos.ENABLED:
                try:
                    act = chaos.point(point, pg=self.pg.pg_id.hex()[:12],
                                      bundle=index,
                                      node=node.node_id.hex()[:12])
                except chaos.ChaosError:
                    self.failed[index] = node
                    continue
                if act is not None and act.kind == "drop":
                    self.failed[index] = node
                    continue
            send.append(index)
        if not send:
            return
        try:
            if method == "prepare_bundle":
                rs = await self.gcs._node_call(
                    node, "prepare_bundles",
                    {"pg_id": self.pg.pg_id,
                     "bundles": [(i, self.pg.bundles[i]) for i in send]})
            else:
                rs = await self.gcs._node_call(
                    node, "commit_bundles",
                    {"pg_id": self.pg.pg_id, "indices": send})
        except Exception:
            rs = None
        for pos, index in enumerate(send):
            ok = bool(rs and pos < len(rs) and rs[pos]
                      and rs[pos].get("ok"))
            (into if ok else self.failed)[index] = node

    async def prepare(self) -> bool:
        """Parallel phase 1. True iff every bundle reserved."""
        return await self._phase("gcs.pg_prepare", "prepare_bundle",
                                 list(self.placement.items()),
                                 self.prepared)

    async def commit(self) -> bool:
        """Parallel phase 2 over the prepared set. Failures (node died
        between phases, injected faults) land in ``failed`` for repair —
        they are NEVER raised out of the transaction."""
        return await self._phase("gcs.pg_commit", "commit_bundle",
                                 list(self.prepared.items()),
                                 self.committed)

    async def rollback(self) -> None:
        """Return every reservation this txn made that did not commit
        (prepared-only slots, plus commit-phase failures whose node may
        still hold the prepared bundle). Best effort: a dead node's
        reservation died with it; a live-but-unreachable node's
        uncommitted one is reclaimed by its own bundle-lease GC, and a
        commit that LANDED but whose ack was lost (lease GC skips
        committed entries) is caught by the GCS's periodic ledger audit
        (_audit_node_bundles)."""
        victims = [(self.pg.pg_id, i, n) for i, n in self.prepared.items()
                   if i not in self.committed]
        await self.gcs._return_bundles(victims)


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: str | None = None):
        self.cfg = get_config()
        self.persist_path = persist_path
        self._dirty = False
        self.server = rpc.make_server(host, port)
        self.server.add_routes(self)
        self.server.on_disconnect = self._on_disconnect
        # Native state engine (C++, _native/src/gcs_core.cc): KV tables,
        # write-ahead journal, snapshot/recovery all live native; this
        # process only dispatches RPCs and runs policy (ref role:
        # src/ray/gcs/gcs_server/store_client/redis_store_client.cc,
        # gcs_table_storage.h)
        from ray_tpu.core.gcs_store import NativeGcsStore

        self.kvstore = NativeGcsStore(persist_path)
        # opt-in machine-crash durability (cfg.gcs_fsync): journaled KV
        # writes are acked only after their WAL record is fdatasync'd,
        # group-committed so every write landing in the same event-loop
        # tick shares ONE disk sync; snapshots fsync before their rename.
        # Default (off) remains process-kill-safe: appends are fflushed to
        # the OS page cache, which survives a GCS crash but not the box.
        self._fsync = bool(getattr(self.cfg, "gcs_fsync", False)) \
            and persist_path is not None
        if self._fsync:
            self.kvstore.set_fsync(True)
        self._sync_fut: asyncio.Future | None = None
        self.nodes: dict[NodeID, NodeInfo] = {}
        self.actors: dict[ActorID, ActorInfo] = {}
        self.named_actors: dict[str, ActorID] = {}
        self.pgs: dict[PlacementGroupID, PlacementGroupInfo] = {}
        self._actor_spread_rr = 0  # SPREAD actor round-robin cursor
        # per-raylet lease-request coalescer (_schedule_actor): concurrent
        # actor creations targeting the same node in one loop tick ride
        # ONE batched lease_workers RPC (one ledger pass raylet-side)
        self._lease_batches: dict[tuple, list] = {}
        self.job_counter = 0
        self.task_events: list[dict] = []  # ring buffer of task lifecycle events
        # trace assembler (utils/tracing.py wire context): span rows
        # riding report_task_events fold into per-trace buckets here, so
        # one request's causal tree is ONE lookup (rpc_get_trace) instead
        # of a scan over the whole event ring. Bounded by
        # cfg.trace_table_max with SLOW-TRACE retention: eviction
        # protects the slowest cfg.trace_slow_keep fraction (the p99
        # outliers tracing exists to explain) and drops the oldest of
        # the rest. Volatile (like task_events): not journaled.
        self.traces: dict[str, dict] = {}
        self._trace_cp_done: set[str] = set()  # critical path computed
        # ns="latency" retention (satellite): last-touch stamps per key;
        # the health loop sweeps entries dead publishers left behind
        self._latency_touched: dict[str, float] = {}
        # timeseries rollup plane (core/metrics_store.py): every
        # ns="metrics" snapshot put folds into ring-buffered 1s/10s/60s
        # windows here, so metric_window/prometheus rates read history
        # instead of the latest value. Volatile like the snapshots.
        from ray_tpu.core.metrics_store import RollupStore

        self.rollups = RollupStore()

        # pubsub: channel -> {Connection}
        self.subs: dict[str, set[rpc.Connection]] = {}
        # connections that are raylets (for health/cleanup): conn -> node_id
        self.raylet_conns: dict[rpc.Connection, NodeID] = {}
        # pooled GCS->raylet connections for short control RPCs (bundle
        # prepare/commit/return): the old per-bundle rpc.connect loop was
        # most of the placement-group benchmark's cost. Never used for
        # parking calls (lease_worker), whose cancel-on-disconnect
        # semantics need a per-request connection.
        self._node_conns: dict[NodeID, rpc.Connection] = {}
        # placement-group reconciliation: pg ids with a drive pass in
        # flight (one reconciler per PG at a time)
        self._pg_reconciling: set[PlacementGroupID] = set()
        # actor worker connections for cleanup: conn -> actor_ids
        self._stopping = False
        self._bg = aio.TaskGroup()

    # ------------------------------------------------------------------ pubsub
    async def publish(self, channel: str, message: Any):
        if channel == "actors":
            # actor-table choke point: every actor state transition
            # publishes here — journal the entry's current state
            aid = message.get("actor_id") if isinstance(message, dict) else None
            info = self.actors.get(aid)
            if info is not None:
                self._journal(("actor", info))
        elif channel in ("pgs",) or channel.startswith("actor:"):
            self.mark_dirty()  # covered by the periodic snapshot
        dead = []
        for conn in self.subs.get(channel, ()):  # push-based: no long-poll
            try:
                await conn.notify("pubsub", {"channel": channel, "message": message})
            except Exception:
                dead.append(conn)
        for conn in dead:
            self.subs.get(channel, set()).discard(conn)

    async def rpc_subscribe(self, conn, p):
        self.subs.setdefault(p["channel"], set()).add(conn)
        return True

    async def rpc_publish(self, conn, p):
        """Client-originated pubsub (ref: GcsPublisher — workers publish
        through the GCS fan-out): the serve controller announces
        autoscale decisions on ``serve_autoscale`` this way."""
        await self.publish(p["channel"], p["message"])
        return True

    # ---------------------------------------------------------------------- kv
    # All KV state lives in the native engine; puts/dels journal to the
    # C++ WAL inside the same native call (GIL released throughout).
    async def rpc_kv_put(self, conn, p):
        ns = p.get("ns", "")
        journal = ns != "metrics"  # metrics are volatile: snapshot-only
        if chaos.ENABLED and journal:
            # "gcs.wal_append" fault point, journaled-KV flavor: an
            # `error` action raises out of this handler, so the client
            # sees a failed (never-acked, never-journaled) write —
            # delay stalls the ack like a slow disk would
            chaos.point("gcs.wal_append", ns=ns, kind="kv_put")
        ok = self.kvstore.put(ns, p["key"], p["value"],
                              overwrite=p.get("overwrite", True),
                              journal=journal)
        if ns == "metrics":
            # rollup ingest rides the same put the snapshot already
            # pays for (worker hex / raylet.<node> keys); a malformed
            # blob must not fail the kv write it piggybacks on
            try:
                self.rollups.ingest(p["key"], pickle.loads(p["value"]))
            except Exception:
                log.debug("metric rollup ingest failed", exc_info=True)
        if ns == "latency":  # retention clock (see _latency_sweep)
            self._latency_touched[p["key"]] = time.monotonic()
        self.mark_dirty()
        if journal:
            await self._commit_barrier()
        return ok

    async def rpc_kv_get(self, conn, p):
        return self.kvstore.get(p.get("ns", ""), p["key"])

    async def rpc_kv_multi_get(self, conn, p):
        return self.kvstore.multi_get(p.get("ns", ""), p["keys"])

    async def rpc_kv_del(self, conn, p):
        ok = self.kvstore.delete(p.get("ns", ""), p["key"])
        self.mark_dirty()
        await self._commit_barrier()
        return ok

    # ------------------------------------------------------- metric rollups
    async def rpc_metric_window(self, conn, p):
        """Windowed rate/quantile series from the rollup plane (since
        2.2): ``{name, type, res, points}`` — see RollupStore.window."""
        return self.rollups.window(p["name"], float(p.get("secs", 60.0)),
                                   tags=p.get("tags"))

    async def rpc_metric_names(self, conn, p):
        """Every metric the rollup plane has seen plus the derived
        ratio series it computes (since 2.2)."""
        return self.rollups.names()

    async def rpc_metric_export(self, conn, p):
        """Trailing per-tag counter rates + ratio values (since 2.2) —
        the prometheus ``:rate<secs>s`` family feed."""
        return self.rollups.export_rates(float(p.get("secs", 10.0)))

    async def _commit_barrier(self):
        """Group commit (cfg.gcs_fsync off = no-op): hold this journaled
        write's ack until its WAL record is on disk. One syncer future per
        event-loop tick — concurrent writers all await the same fdatasync
        (the classic group-commit amortization), which runs in an executor
        with the GIL released. A FAILED sync raises: the caller's RPC
        errors out instead of acking a write that is not durable (the
        whole point of the opt-in mode)."""
        if not self._fsync:
            return
        loop = asyncio.get_running_loop()
        fut = self._sync_fut
        if fut is None:
            fut = loop.create_future()
            self._sync_fut = fut

            async def sync(fut=fut):
                ok = False
                try:
                    await asyncio.sleep(0)  # let batch-mates append first
                    self._sync_fut = None
                    ok = await loop.run_in_executor(
                        None, self.kvstore.wal_sync)
                finally:
                    # cancellation-safe (stop() cancels _bg tasks while
                    # writers may be parked on fut): ALWAYS resolve the
                    # barrier and clear the slot, or those writers — and
                    # every later one finding the dead future — hang
                    if self._sync_fut is fut:
                        self._sync_fut = None
                    if not fut.done():
                        fut.set_result(ok)

            if self._bg.spawn(sync()) is None and not fut.done():
                # shutting down: sync inline rather than faking success
                # (stop()'s final snapshot has not happened yet). Clear
                # the slot — sync() never ran, and leaving a completed
                # future here would ack every later write without a sync.
                self._sync_fut = None
                fut.set_result(self.kvstore.wal_sync())
        if not await fut:
            raise RuntimeError(
                "GCS WAL fdatasync failed: write is NOT durable "
                "(gcs_fsync mode refuses to ack it)")

    def _kick_sync(self):
        """Fire-and-forget group sync for table-op journal records (actor
        transitions, job counters): the records reach disk promptly via
        the shared syncer, without withholding the mutation's reply."""
        if not self._fsync:
            return
        try:
            self._bg.spawn(self._commit_barrier())
        except RuntimeError:
            pass  # no running loop (restore path): snapshot covers it

    async def rpc_kv_exists(self, conn, p):
        return self.kvstore.exists(p.get("ns", ""), p["key"])

    async def rpc_kv_keys(self, conn, p):
        return self.kvstore.keys(p.get("ns", ""), p.get("prefix", ""))

    # -------------------------------------------------------------------- jobs
    async def rpc_register_job(self, conn, p):
        self.job_counter += 1
        self._journal(("job", self.job_counter))
        return JobID(self.job_counter.to_bytes(4, "little"))

    # ----------------------------------------------- pooled raylet control RPC
    async def _node_conn(self, node: NodeInfo) -> rpc.Connection:
        conn = self._node_conns.get(node.node_id)
        if conn is not None and not conn._closed:
            return conn
        conn = await rpc.connect(*node.address,
                                 timeout=self.cfg.rpc_connect_timeout_s)
        cur = self._node_conns.get(node.node_id)
        if cur is not None and not cur._closed:
            # lost a concurrent-dial race (parallel 2PC legs to one
            # node): keep the pooled winner, close ours — overwriting
            # would leak the first socket until process exit
            self._bg.spawn(conn.close())
            return cur
        self._node_conns[node.node_id] = conn
        return conn

    def _drop_node_conn(self, node_id: NodeID) -> None:
        conn = self._node_conns.pop(node_id, None)
        if conn is not None:
            self._bg.spawn(conn.close())

    async def _node_call(self, node: NodeInfo, method: str, payload: dict,
                         timeout: float | None = None):
        """One short control RPC over the pooled connection. A pooled
        socket that died since its last use is replaced and the call
        retried ONCE on the fresh dial; a failure on the fresh socket is
        the node's problem and propagates."""
        for attempt in (0, 1):
            try:
                conn = await self._node_conn(node)
                return await conn.call(method, payload, timeout=timeout)
            except (rpc.RpcError, OSError, asyncio.TimeoutError):
                self._drop_node_conn(node.node_id)
                if attempt:
                    raise

    async def _return_bundles(
            self,
            victims: list[tuple[PlacementGroupID, int, NodeInfo]]) -> None:
        """Parallel best-effort bundle returns (2PC rollback/repair,
        remove and drain paths). Dead or unreachable nodes are skipped —
        their reservations are reclaimed by the raylet bundle-lease GC
        or died with the process."""

        async def one(pg_id: PlacementGroupID, index: int, node: NodeInfo):
            try:
                # no wait_for (Task-wrap cost, see BundleTxn._phase_one):
                # a dying node's pooled conn drop fails this call instead
                await self._node_call(
                    node, "return_bundle",
                    {"pg_id": pg_id, "bundle_index": index})
            except Exception:
                log.debug("bundle return failed on %s",
                          node.node_id.hex()[:12], exc_info=True)

        live = [(p, i, n) for p, i, n in victims
                if self.nodes.get(n.node_id) is not None
                and self.nodes[n.node_id].alive]
        if len(live) == 1:
            await one(*live[0])  # skip the gather wrapping (see _phase)
        elif live:
            await asyncio.gather(*(one(p, i, n) for p, i, n in live))

    # ------------------------------------------------------------------- nodes
    async def rpc_register_node(self, conn, p):
        info = NodeInfo(
            node_id=p["node_id"],
            address=tuple(p["address"]),
            store_name=p["store_name"],
            resources_total=dict(p["resources"]),
            resources_available=dict(p["resources"]),
            labels=p.get("labels", {}),
            pid=int(p.get("pid", 0)),
        )
        self.nodes[info.node_id] = info
        self._drop_node_conn(info.node_id)  # pooled socket may predate a restart
        # a re-registering raylet (GCS-FT reconnect) replaces its old
        # connection mapping, so the old socket's close is a no-op
        for old_conn, nid in list(self.raylet_conns.items()):
            if nid == info.node_id and old_conn is not conn:
                self.raylet_conns.pop(old_conn, None)
        self.raylet_conns[conn] = info.node_id
        # bundle reconciliation (GCS FT): the raylet reports every bundle
        # reservation its ledger holds; reservations the recovered pgs
        # table doesn't recognize are returned, committed ones it does are
        # adopted back into bundle_nodes (the table may have been restored
        # from a snapshot older than the placement)
        stale = self._reconcile_reported_bundles(
            info, p.get("bundles") or ())
        await self.publish("nodes", {"event": "added", "node": info.view()})
        # fresh capacity: wake PENDING (infeasible-at-create) and
        # RESCHEDULING placement groups
        self._kick_pgs()
        return {"node_id": info.node_id, "cluster": self.cluster_view(),
                "return_bundles": stale}

    def _reconcile_reported_bundles(self, info: NodeInfo, reported,
                                    live_audit: bool = False) -> list[tuple]:
        stale: list[tuple] = []
        for b in reported:
            pg_id, index = b["pg_id"], int(b["bundle_index"])
            pg = self.pgs.get(pg_id)
            if (pg is None or pg.state == "REMOVED"
                    or index >= len(pg.bundles)):
                stale.append((pg_id, index))
                continue
            if not b.get("committed"):
                # registration path: a reservation the coordinating 2PC
                # never committed (it died with the old GCS) — return it.
                # Live-audit path: this may be THIS GCS's own prepare in
                # flight between the phases — leave it to the raylet's
                # bundle-lease GC.
                if not live_audit:
                    stale.append((pg_id, index))
                continue
            if len(pg.bundle_nodes) != len(pg.bundles):
                pg.bundle_nodes = [None] * len(pg.bundles)
            current = pg.bundle_nodes[index]
            if current is not None and current != info.node_id:
                # rescheduled elsewhere while this node was away: its old
                # copy of the bundle is stale capacity
                stale.append((pg_id, index))
            elif current is None and pg_id in self._pg_reconciling:
                # a repair txn for this PG is mid-flight and may be about
                # to commit this very slot on another node — adopting now
                # would be overwritten by the commit and strand this
                # node's committed reservation forever (the lease GC only
                # reclaims uncommitted ones). Return it; the txn's
                # outcome is authoritative.
                stale.append((pg_id, index))
            else:
                pg.bundle_nodes[index] = info.node_id
        return stale

    async def rpc_heartbeat(self, conn, p):
        info = self.nodes.get(p["node_id"])
        if info is None:
            return {"ok": False}
        info.last_heartbeat = time.monotonic()
        # queued_leases is a latest-wins scalar independent of the versioned
        # resource view: apply it even on stale frames so the autoscaler
        # demand signal tracks the most recent report
        if "queued_leases" in p:
            info.queued_leases = int(p.get("queued_leases", 0))
        version = int(p.get("version", 0))
        if version and version <= info.view_version:
            # stale or reordered report (e.g. a delayed frame after a GCS
            # reconnect): liveness refreshed above, view NOT applied
            return {"ok": True, "stale": True}
        if p.get("resources_available") is not None:
            changed = info.resources_available != p["resources_available"]
            info.resources_available = dict(p["resources_available"])
            if version:
                info.view_version = version
            if changed:
                # versioned resource-view gossip to all raylets (the
                # RaySyncer role, ref: ray_syncer.h:83)
                await self.publish("nodes", {"event": "updated", "node": info.view()})
        return {"ok": True}

    async def rpc_get_cluster(self, conn, p):
        return self.cluster_view()

    def cluster_view(self) -> list[dict]:
        return [n.view() for n in self.nodes.values() if n.alive]

    async def rpc_drain_node(self, conn, p):
        node_id = p["node_id"]
        info = self.nodes.get(node_id)
        if info is not None and info.alive:
            # graceful half of a drain: hand the node's bundle
            # reservations back (one parallel wave) while its raylet is
            # still up, so the ledger frees NOW instead of waiting on
            # the raylet-side bundle-lease GC after the dead-mark
            victims = []
            for pg in self.pgs.values():
                if pg.state == "REMOVED":
                    continue
                victims.extend(
                    (pg.pg_id, i, info)
                    for i, nid in enumerate(pg.bundle_nodes)
                    if nid == node_id)
            await self._return_bundles(victims)
        await self._mark_node_dead(node_id, "drained")
        return True

    async def _mark_node_dead(self, node_id: NodeID, cause: str):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        self._drop_node_conn(node_id)
        await self.publish("nodes", {"event": "removed", "node_id": node_id, "cause": cause})
        # dedicated low-traffic channel for location-cache invalidation:
        # every CoreClient subscribes to THIS, not "nodes" — the "nodes"
        # channel also carries per-heartbeat resource gossip that every
        # driver and worker would otherwise receive and discard
        await self.publish("node_removed", {"node_id": node_id})
        # placement groups FIRST (before the actor failover below): a
        # PG-bound actor rescheduling must observe RESCHEDULING and wait
        # for the repair — not a still-CREATED pg whose bundle_nodes
        # point at the dead node, which would spin its _pick_node loop
        # against a bundle that can never grant until the start timeout
        # killed it
        for pg in list(self.pgs.values()):
            if pg.state not in ("CREATED", "RESCHEDULING"):
                continue
            lost = [i for i, nid in enumerate(pg.bundle_nodes)
                    if nid == node_id]
            if lost:
                await self._reschedule_lost(
                    pg, lost, f"node {node_id.hex()[:12]} {cause}")
        # fail actors living on that node (ref: gcs_actor_manager.cc OnNodeDead)
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (ALIVE, PENDING):
                await self._on_actor_failure(actor, f"node {node_id} died: {cause}")

    # ------------------------------------------------------------------ actors
    async def rpc_register_actor(self, conn, p):
        spec = p["spec"]
        actor_id = spec["actor_id"]
        name = spec.get("name")
        if name:
            if name in self.named_actors:
                existing = self.actors.get(self.named_actors[name])
                if existing is not None and existing.state != DEAD:
                    if spec.get("get_if_exists"):
                        return existing.view()
                    raise ValueError(f"actor name {name!r} already taken")
        info = ActorInfo(
            actor_id=actor_id,
            name=name,
            state=PENDING,
            spec=spec,
            max_restarts=spec.get("max_restarts", 0),
        )
        self.actors[actor_id] = info
        self._journal(("actor", info))
        if name:
            self.named_actors[name] = actor_id
            self._journal(("name", name, actor_id))
        self._bg.spawn(self._schedule_actor(info))
        return info.view()

    async def _schedule_actor(self, info: ActorInfo):
        """GCS-side actor scheduling (ref: gcs_actor_scheduler.h): lease a
        worker from a raylet chosen by resource fit, then push the creation
        task to that worker directly. Lease races and raylet deaths
        retry with exponential backoff + jitter under ONE
        worker_start_timeout_s deadline (the old path respawned itself
        with a flat 0.05s sleep and a fresh deadline every time —
        raylint RT013's synchronized-herd shape, and an actor could
        retry forever)."""
        try:
            resources = info.spec.get("resources", {"CPU": 1.0})
            pg_id = info.spec.get("placement_group")
            bundle_index = info.spec.get("bundle_index", -1)
            strategy = info.spec.get("scheduling_strategy")
            deadline = time.monotonic() + self.cfg.worker_start_timeout_s
            retries = 0
            while True:
                node = self._pick_node(resources, pg_id, bundle_index,
                                       strategy)
                if node is None:
                    if time.monotonic() > deadline:
                        info.state = DEAD
                        info.death_cause = (
                            f"no node can host actor resources {resources}"
                            + (f" under strategy {strategy}" if strategy
                               else "")
                            + (" (placement group not CREATED)"
                               if pg_id is not None else ""))
                        await self.publish("actors", info.view())
                        await self.publish(
                            f"actor:{info.actor_id.hex()}", info.view())
                        return
                    await asyncio.sleep(0.1)  # poll: placement may repair
                    continue
                # leases ride the batched lease_workers path (2.0):
                # concurrent actor creations targeting the same raylet
                # coalesce into ONE RPC and one ledger pass; the batched
                # handler never parks (busy replies retry here), so no
                # cancel-on-disconnect concern remains
                lease = None
                try:
                    lease = await self._lease_via_batch(
                        node,
                        {"resources": resources,
                         "for_actor": info.actor_id,
                         "pg_id": pg_id, "bundle_index": bundle_index},
                        timeout=max(1.0, deadline - time.monotonic()),
                    )
                except (rpc.RpcError, OSError, asyncio.TimeoutError):
                    # chosen raylet died or stalled mid-grant: re-pick —
                    # node death will have updated self.nodes by the time
                    # the backoff elapses
                    log.debug("actor lease attempt on %s failed",
                              node.node_id.hex()[:12], exc_info=True)
                if lease and lease.get("granted"):
                    break
                if time.monotonic() > deadline:
                    info.state = DEAD
                    info.death_cause = (
                        f"actor lease not granted within "
                        f"worker_start_timeout_s="
                        f"{self.cfg.worker_start_timeout_s}")
                    await self.publish("actors", info.view())
                    await self.publish(
                        f"actor:{info.actor_id.hex()}", info.view())
                    return
                retries += 1
                base = min(0.05 * (2 ** min(retries, 5)), 1.0)
                await asyncio.sleep(base * (0.5 + random.random() / 2))

            worker_addr = tuple(lease["worker_address"])
            wconn = await rpc.connect(*worker_addr)
            try:
                await wconn.call(
                    "create_actor",
                    {"spec": info.spec, "tpu_chips": lease.get("tpu_chips")},
                    timeout=self.cfg.worker_start_timeout_s,
                )
            finally:
                await wconn.close()
            info.state = ALIVE
            info.address = worker_addr
            info.node_id = node.node_id
            await self.publish("actors", info.view())
            await self.publish(f"actor:{info.actor_id.hex()}", info.view())
        except Exception as e:  # scheduling failed terminally
            info.state = DEAD
            info.death_cause = f"actor creation failed: {e!r}"
            await self.publish("actors", info.view())
            await self.publish(f"actor:{info.actor_id.hex()}", info.view())

    async def _lease_via_batch(self, node: "NodeInfo", payload: dict,
                               timeout: float):
        """Coalesced actor-lease request: every request targeting the
        same raylet address queued within one loop tick ships as ONE
        ``lease_workers`` call (a serve scale-up creating N replicas
        pays one RPC + one ledger pass instead of N). Goes over a
        per-batch transient connection, like the old per-request dial."""
        addr = tuple(node.address)
        fut = asyncio.get_running_loop().create_future()
        q = self._lease_batches.setdefault(addr, [])
        q.append((payload, fut))
        if len(q) == 1:
            # flush NEXT tick so same-tick siblings can pile on
            asyncio.get_running_loop().call_soon(
                lambda: self._bg.spawn(self._flush_lease_batch(addr)))
        return await asyncio.wait_for(fut, timeout)

    async def _flush_lease_batch(self, addr: tuple) -> None:
        batch = self._lease_batches.pop(addr, [])
        if not batch:
            return
        payloads = [p for p, _ in batch]
        replies = None
        err: Exception | None = None
        try:
            conn = await rpc.connect(*addr, timeout=5)
            try:
                replies = await conn.call(
                    "lease_workers", {"requests": payloads},
                    timeout=self.cfg.worker_start_timeout_s + 10)
            finally:
                await conn.close()
        except Exception as e:
            err = e if isinstance(e, Exception) else rpc.RpcError(repr(e))
        for i, (_, fut) in enumerate(batch):
            rep = (replies[i] if replies is not None and i < len(replies)
                   else None)
            if fut.done():
                # caller timed out/cancelled while the grant was in
                # flight: nobody owns this lease now — return it, or the
                # worker and its allocation leak (actor leases are not
                # owner_bound, so no disconnect sweep reclaims them)
                if rep and rep.get("granted"):
                    self._bg.spawn(self._return_orphan_lease(addr, rep))
                continue
            if err is not None or rep is None:
                fut.set_exception(
                    err or rpc.RpcError("short lease_workers reply"))
            else:
                fut.set_result(rep)

    async def _return_orphan_lease(self, addr: tuple, rep: dict) -> None:
        """Best-effort return (kill: single-purpose actor worker) of a
        batched lease whose requester gave up before the grant landed."""
        try:
            conn = await rpc.connect(*addr, timeout=5)
            try:
                await conn.call("return_lease",
                                {"lease_id": rep["lease_id"], "kill": True},
                                timeout=10)
            finally:
                await conn.close()
        except Exception:
            log.debug("orphan lease return failed", exc_info=True)

    def _pick_node(self, resources, pg_id=None, bundle_index=-1,
                   strategy=None) -> NodeInfo | None:
        if pg_id is not None:
            pg = self.pgs.get(pg_id)
            if pg is None or pg.state != "CREATED":
                return None
            candidates = (
                [pg.bundle_nodes[bundle_index]]
                if bundle_index >= 0
                else list(dict.fromkeys(pg.bundle_nodes))
            )
            for nid in candidates:
                node = self.nodes.get(nid)
                if node and node.alive and _fits(resources, node.resources_available):
                    return node
            return None
        fitting = [node for node in self.nodes.values()
                   if node.alive and _fits(resources, node.resources_available)]
        if strategy is not None:
            # actor-site scheduling strategies (ref: gcs_actor_scheduler
            # consulting the cluster scheduling policies)
            from ray_tpu.util.scheduling_strategies import labels_match

            t = strategy.get("type")
            if t == "node_affinity":
                node = next((n for n in fitting
                             if n.node_id.hex() == strategy["node_id"]), None)
                if node is not None or not strategy.get("soft"):
                    return node  # hard: only that node (None => retry/DEAD)
            elif t == "spread":
                self._actor_spread_rr += 1
                ordered = sorted(fitting, key=lambda n: n.node_id.hex())
                if ordered:
                    return ordered[self._actor_spread_rr % len(ordered)]
                return None
            elif t == "node_label":
                hard = strategy.get("hard", {})
                soft = strategy.get("soft", {})
                matching = [n for n in fitting
                            if labels_match(n.labels, hard)]
                preferred = [n for n in matching
                             if labels_match(n.labels, soft)]
                fitting = preferred or matching
        # hybrid top-k (ref: hybrid_scheduling_policy.h:50 + policy/scorer.h,
        # shared impl in core/policy.py): randomize among comfortable nodes,
        # deterministic best when everything is tight.
        scored = [
            (policy.score(resources, node.resources_total,
                          node.resources_available), node)
            for node in fitting
        ]
        return policy.pick(scored)

    async def rpc_get_actor(self, conn, p):
        actor_id = p.get("actor_id")
        if actor_id is None:
            actor_id = self.named_actors.get(p["name"])
            if actor_id is None:
                return None
        info = self.actors.get(actor_id)
        return info.view() if info else None

    async def rpc_list_actors(self, conn, p):
        return [a.view() for a in self.actors.values()]

    async def rpc_list_placement_groups(self, conn, p):
        return [self._pg_view(pg) for pg in self.pgs.values()]

    async def rpc_report_actor_death(self, conn, p):
        info = self.actors.get(p["actor_id"])
        if info is not None and info.state != DEAD:
            await self._on_actor_failure(info, p.get("cause", "actor process died"))
        return True

    async def rpc_kill_actor(self, conn, p):
        info = self.actors.get(p["actor_id"])
        if info is None:
            return False
        info.max_restarts = 0  # explicit kill never restarts
        if info.address is not None:
            try:
                wconn = await rpc.connect(*info.address, timeout=2)
                await wconn.notify("exit_worker", {"force": not p.get("no_restart", False)})
                await wconn.close()
            except (rpc.RpcError, OSError):
                pass  # worker already dead: the kill is moot
        await self._on_actor_failure(info, "killed via kill_actor")
        return True

    async def _on_actor_failure(self, info: ActorInfo, cause: str):
        if info.num_restarts < info.max_restarts:
            info.num_restarts += 1
            info.state = RESTARTING
            info.address = None
            info.node_id = None
            await self.publish("actors", info.view())
            await self.publish(f"actor:{info.actor_id.hex()}", info.view())
            self._bg.spawn(self._schedule_actor(info))
        else:
            info.state = DEAD
            info.death_cause = cause
            info.address = None
            await self.publish("actors", info.view())
            await self.publish(f"actor:{info.actor_id.hex()}", info.view())
            if info.name and self.named_actors.get(info.name) == info.actor_id:
                del self.named_actors[info.name]
                self._journal(("namedel", info.name))

    # -------------------------------------------------------- placement groups
    # PGs are a RECONCILED desired state, not a one-shot RPC (ref:
    # gcs_placement_group_mgr.h:232 + the Borg model of placement as a
    # converged spec): _drive_pg runs the two-phase reservation through a
    # BundleTxn with parallel prepare/commit over pooled connections,
    # repairs commit-phase failures by re-placing exactly the failed
    # bundles, and is re-kicked by node registration, node death, and the
    # health-loop sweep until the PG converges (or is removed).

    async def rpc_create_placement_group(self, conn, p):
        """Two-phase bundle reservation across raylets (ref:
        gcs_placement_group_scheduler.h:288 prepare/commit protocol)."""
        pg_id = p["pg_id"]
        bundles = p["bundles"]
        strategy = p.get("strategy", "PACK")
        pg = PlacementGroupInfo(
            pg_id=pg_id, bundles=bundles, strategy=strategy, state="PENDING",
            bundle_nodes=[None] * len(bundles))
        self.pgs[pg_id] = pg
        self._journal(("pg", pg))
        await self._reconcile_pg(pg)
        if pg.state == "CREATED":
            return {"state": "CREATED",
                    "bundle_nodes": list(pg.bundle_nodes)}
        # infeasible now: the PG stays PENDING and a later node
        # registration wakes it (the caller's ready()/wait observes the
        # transition via the "pgs" pubsub channel)
        return {"state": "INFEASIBLE"}

    async def _reschedule_lost(self, pg: PlacementGroupInfo,
                               lost: list[int], cause: str) -> None:
        """Shared node-loss bookkeeping (_mark_node_dead and the
        GCS-restart sweep): null the lost slots, move to RESCHEDULING,
        stamp the cause, journal + publish the transition, kick the
        reconciler (a no-op while a pass is in flight — that pass's
        liveness re-check picks the loss up instead)."""
        for i in lost:
            pg.bundle_nodes[i] = None
        pg.state = "RESCHEDULING"
        pg.reschedules += 1
        pg.reschedule_cause = cause
        self._journal(("pg", pg))
        await self._publish_pg(pg)
        self._kick_pg(pg)

    async def _audit_node_bundles(self, info: NodeInfo) -> None:
        """Audit one live node's bundle ledger against the pgs table:
        reservations the table doesn't assign to this node are returned
        (stranded committed bundles included), recognized committed ones
        are adopted — the same reconciliation re-registration runs,
        initiated server-side on the health-loop cadence."""
        try:
            held = await self._node_call(info, "list_bundles", {})
        except Exception:
            log.debug("bundle audit of %s failed",
                      info.node_id.hex()[:12], exc_info=True)
            return
        stale = self._reconcile_reported_bundles(info, held or (),
                                                 live_audit=True)
        if stale:
            await self._return_bundles(
                [(pg_id, index, info) for pg_id, index in stale])

    def _kick_pg(self, pg: PlacementGroupInfo) -> None:
        if (pg.state in ("PENDING", "RESCHEDULING")
                and pg.pg_id not in self._pg_reconciling):
            self._bg.spawn(self._reconcile_pg(pg))

    def _kick_pgs(self) -> None:
        for pg in list(self.pgs.values()):
            self._kick_pg(pg)

    async def _reconcile_pg(self, pg: PlacementGroupInfo) -> None:
        """Serialized entry: at most one drive pass per PG in flight."""
        if pg.pg_id in self._pg_reconciling:
            return
        self._pg_reconciling.add(pg.pg_id)
        try:
            await self._drive_pg(pg)
        finally:
            self._pg_reconciling.discard(pg.pg_id)

    async def _drive_pg(self, pg: PlacementGroupInfo) -> None:
        """One reconciliation pass: place every unassigned/lost bundle,
        2PC the placement, repair per-bundle failures by re-placing them
        on other nodes. Leaves the PG PENDING/RESCHEDULING when the
        cluster can't satisfy it right now — node registration or the
        health-loop sweep kicks another pass later."""
        bad: set[NodeID] = set()  # nodes that failed a phase this pass
        failures = 0
        for _round in range(16):  # hard cap: the next kick resumes
            if pg.state not in ("PENDING", "RESCHEDULING"):
                return
            if len(pg.bundle_nodes) != len(pg.bundles):
                pg.bundle_nodes = [None] * len(pg.bundles)
            alive = {nid for nid, n in self.nodes.items() if n.alive}
            lost = pg.lost_indices(alive)
            if not lost:
                # the liveness check above is the ONLY gate to CREATED:
                # a node death that landed while this pass was awaiting
                # a 2PC phase (its _kick_pg no-opped on the reconciling
                # guard) shows up here as a fresh lost slot and loops
                # back into placement instead of being declared CREATED
                # with a dead/None bundle_nodes entry
                await self._pg_created(pg)
                return
            if failures >= 4:
                break
            for i in lost:
                pg.bundle_nodes[i] = None
            survivors = {nid for nid in pg.bundle_nodes if nid is not None}
            placement = self._place_bundles(
                [pg.bundles[i] for i in lost], pg.strategy,
                exclude=bad, used=survivors)
            if placement is None:
                if bad:
                    # a phase-failed node may have been a transient fault,
                    # not a death: widen the candidate set once before
                    # giving up the pass
                    bad.clear()
                    continue
                return  # infeasible now; stays PENDING/RESCHEDULING
            txn = BundleTxn(self, pg, dict(zip(lost, placement)))
            if not await txn.prepare():
                await txn.rollback()
                bad.update(n.node_id for n in txn.failed.values())
                failures += 1
                continue
            await txn.commit()
            if pg.state == "REMOVED":
                # removal raced the commit: hand everything straight back
                await self._return_bundles(
                    [(pg.pg_id, i, n) for i, n in txn.placement.items()])
                return
            for index, node in txn.committed.items():
                pg.bundle_nodes[index] = node.node_id
            if txn.failed:
                # commit-phase failures (node died between phases /
                # injected fault): REPAIR — return what may still be
                # reserved there and re-place just those bundles — never
                # raise out with reservations stranded
                await txn.rollback()
                bad.update(n.node_id for n in txn.failed.values())
                failures += 1
            # success or repair: loop back to the liveness re-check
        log.warning("placement group %s did not converge this pass "
                    "(state=%s); will retry on the next kick",
                    pg.pg_id.hex()[:12], pg.state)

    async def _pg_created(self, pg: PlacementGroupInfo) -> None:
        pg.state = "CREATED"
        self._journal(("pg", pg))
        await self._publish_pg(pg)

    def _pg_view(self, pg: PlacementGroupInfo) -> dict:
        return {
            "pg_id": pg.pg_id.hex(),
            "bundles": pg.bundles,
            "strategy": pg.strategy,
            "state": pg.state,
            "bundle_nodes": [n.hex() if n is not None else None
                             for n in pg.bundle_nodes],
            "reschedule_cause": pg.reschedule_cause,
            "reschedules": pg.reschedules,
        }

    async def _publish_pg(self, pg: PlacementGroupInfo) -> None:
        await self.publish("pgs", dict(self._pg_view(pg), ts=time.time()))

    def _place_bundles(self, bundles, strategy, *,
                       exclude: set | frozenset = frozenset(),
                       used: set | frozenset = frozenset(),
                       ) -> list[NodeInfo] | None:
        """Place ``bundles`` on alive nodes. ``exclude`` removes nodes
        from candidacy entirely (repair passes exclude nodes that just
        failed a 2PC phase); ``used`` seeds the spread constraint with
        nodes already holding SURVIVING bundles of the same PG, so a
        STRICT_SPREAD repair never doubles up on a survivor."""
        alive = [n for n in self.nodes.values()
                 if n.alive and n.node_id not in exclude]
        avail = {n.node_id: dict(n.resources_available) for n in alive}

        def take(node, bundle):
            for k, v in bundle.items():
                if avail[node.node_id].get(k, 0.0) < v - 1e-9:
                    return False
            for k, v in bundle.items():
                avail[node.node_id][k] -= v
            return True

        assignment: list[NodeInfo] = []
        if strategy in ("STRICT_PACK", "PACK"):
            # try to fit everything on one node first; a partial
            # STRICT_PACK repair must land on the node holding the
            # surviving bundles (there is at most one by construction)
            candidates = ([n for n in alive if n.node_id in used]
                          if strategy == "STRICT_PACK" and used else alive)
            for n in candidates:
                snapshot = dict(avail[n.node_id])
                if _fits_all(bundles, snapshot):
                    for b in bundles:
                        take(n, b)
                    return [n] * len(bundles)
            if strategy == "STRICT_PACK":
                return None
        if strategy in ("SPREAD", "STRICT_SPREAD", "PACK"):
            nodes_sorted = sorted(alive, key=lambda n: -sum(avail[n.node_id].values()))
            pg_used: set[NodeID] = set(used)
            for b in bundles:
                placed = False
                for n in nodes_sorted:
                    if strategy == "STRICT_SPREAD" and n.node_id in pg_used:
                        continue
                    if take(n, b):
                        assignment.append(n)
                        pg_used.add(n.node_id)
                        placed = True
                        break
                if not placed:
                    return None
            return assignment
        return None

    async def rpc_remove_placement_group(self, conn, p):
        pg = self.pgs.get(p["pg_id"])
        if pg is None:
            return False
        victims = [(pg.pg_id, i, self.nodes[nid])
                   for i, nid in enumerate(pg.bundle_nodes)
                   if nid is not None and nid in self.nodes]
        pg.state = "REMOVED"  # set BEFORE the returns: an in-flight
        pg.bundle_nodes = []  # reconcile pass observes it and backs out
        self._journal(("pg", pg))
        await self._return_bundles(victims)
        await self._publish_pg(pg)
        return True

    async def rpc_get_placement_group(self, conn, p):
        pg = self.pgs.get(p["pg_id"])
        if pg is None:
            return None
        return {"state": pg.state, "bundle_nodes": list(pg.bundle_nodes),
                "bundles": pg.bundles, "strategy": pg.strategy,
                "reschedule_cause": pg.reschedule_cause,
                "reschedules": pg.reschedules}

    # -------------------------------------------------- task events / timeline
    async def rpc_report_task_events(self, conn, p):
        events = p["events"]
        self.task_events.extend(events)
        cap = getattr(self.cfg, "gcs_task_events_cap", 100_000)
        if len(self.task_events) > cap:
            del self.task_events[: len(self.task_events) - cap]
        for ev in events:
            if ev.get("state") == "SPAN":
                self._trace_ingest(ev)
        return True

    async def rpc_get_task_events(self, conn, p):
        events = self.task_events
        if p.get("span_only"):
            events = [e for e in events if e.get("state") == "SPAN"]
        offset = int(p.get("offset") or 0)
        limit = p.get("limit")
        if offset:
            events = events[:-offset] if offset < len(events) else []
        if limit is not None:
            events = events[-int(limit):]
        return list(events)

    # ------------------------------------------------- trace assembler
    def _trace_ingest(self, ev: dict) -> None:
        """Fold one span row into its trace bucket (report ingest)."""
        span = ev.get("span") or {}
        trace_id = span.get("trace_id")
        if not trace_id:
            return
        row = {**span,
               "task_id": ev.get("task_id"),
               "worker_id": ev.get("worker_id"),
               "node_id": ev.get("node_id"),
               "pid": ev.get("pid")}
        tr = self.traces.get(trace_id)
        if tr is None:
            if len(self.traces) >= max(2, self.cfg.trace_table_max):
                self._trace_evict()
            tr = self.traces[trace_id] = {
                "spans": [], "start_ts": row.get("start_ts", 0.0),
                "end_ts": row.get("end_ts", 0.0),
                "touched": time.monotonic()}
        if len(tr["spans"]) < max(8, self.cfg.trace_spans_max):
            tr["spans"].append(row)
        tr["start_ts"] = min(tr["start_ts"], row.get("start_ts", tr["start_ts"]))
        tr["end_ts"] = max(tr["end_ts"], row.get("end_ts", tr["end_ts"]))
        tr["touched"] = time.monotonic()
        # NOTE: _trace_cp_done stays sticky — a straggler span landing
        # after the critical-path pass joins the assembled trace (the
        # get_trace view recomputes live) but must not re-OBSERVE the
        # whole stage set into the histogram (metrics are once per trace)

    def _trace_evict(self) -> None:
        """Slow-trace retention: protect the slowest ``trace_slow_keep``
        fraction (by root wall duration), evict the OLDEST of the rest —
        the p99 outlier you will be paged about at 3am survives, the
        10,000 identical fast requests around it are sampled by age."""
        items = list(self.traces.items())
        keep = max(1, int(len(items) * self.cfg.trace_slow_keep))
        by_dur = sorted(items, key=lambda kv: kv[1]["end_ts"] - kv[1]["start_ts"],
                        reverse=True)
        protected = {tid for tid, _ in by_dur[:keep]}
        evictable = [(tid, tr) for tid, tr in items if tid not in protected]
        if not evictable:
            evictable = items
        victim = min(evictable, key=lambda kv: kv[1]["touched"])[0]
        self.traces.pop(victim, None)
        self._trace_cp_done.discard(victim)

    def _trace_view(self, trace_id: str, tr: dict,
                    with_spans: bool) -> dict:
        spans = tr["spans"]
        procs = {(s.get("node_id"), s.get("pid")) for s in spans}
        view = {
            "trace_id": trace_id,
            "start_ts": tr["start_ts"],
            "end_ts": tr["end_ts"],
            "dur_ms": max(0.0, tr["end_ts"] - tr["start_ts"]) * 1e3,
            "n_spans": len(spans),
            "procs": len(procs),
        }
        # root name: earliest parentless span — O(n), no critical-path
        # interval math (list_traces runs this per trace per poll)
        ids = {s.get("span_id") for s in spans}
        roots = [s for s in spans if s.get("parent_span_id") not in ids]
        if roots:
            view["root_name"] = min(
                roots, key=lambda s: s.get("start_ts", 0.0)).get("name")
        if with_spans:
            from ray_tpu.utils.tracing import TraceCriticalPath

            view["spans"] = sorted(spans,
                                   key=lambda s: s.get("start_ts", 0.0))
            view["critical_path"] = TraceCriticalPath.compute(spans)
        return view

    async def rpc_get_trace(self, conn, p):
        tr = self.traces.get(p["trace_id"])
        if tr is None:
            return None
        return self._trace_view(p["trace_id"], tr, with_spans=True)

    async def rpc_list_traces(self, conn, p):
        rows = [self._trace_view(tid, tr, with_spans=False)
                for tid, tr in self.traces.items()]
        rows.sort(key=lambda r: r["start_ts"], reverse=True)
        offset = int(p.get("offset") or 0)
        limit = int(p.get("limit") or 1000)
        return rows[offset:offset + limit]

    _CP_BOUNDS = (10.0, 100.0, 1e3, 1e4, 1e5, 1e6, 1e7)

    def _trace_metrics_tick(self) -> None:
        """Critical-path pass over QUIESCED traces (no new span for >2
        flush intervals): attribute each sampled request's latency to
        queue/exec/wire/pull once and publish the
        ``rt_request_critical_path_us`` histogram into the volatile
        ns="metrics" kv beside the workers' snapshots (the dashboard and
        prometheus_metrics merge it for free). Cells are HAND-ROLLED
        per-stage, never the process-global metrics registry: an
        in-process GCS (the default ``ray_tpu.init()`` topology) shares
        that registry with the driver, whose own flush already publishes
        it — re-publishing the shared snapshot under a second key would
        double-count every driver metric."""
        from ray_tpu.utils.tracing import TraceCriticalPath

        cells = getattr(self, "_cp_cells", None)
        if cells is None:
            cells = self._cp_cells = {}
        quiet = time.monotonic() - 2.0 * max(
            0.5, self.cfg.task_events_report_interval_s)
        fresh = False
        for trace_id, tr in list(self.traces.items()):
            if trace_id in self._trace_cp_done or tr["touched"] > quiet:
                continue
            self._trace_cp_done.add(trace_id)
            cp = TraceCriticalPath.compute(tr["spans"])
            if cp is None:
                continue
            fresh = True
            for stage, us in cp["stages"].items():
                if us <= 0:
                    continue
                cell = cells.setdefault(
                    stage, {"counts": [0] * (len(self._CP_BOUNDS) + 1),
                            "sum": 0.0})
                i = 0
                while i < len(self._CP_BOUNDS) and us > self._CP_BOUNDS[i]:
                    i += 1
                cell["counts"][i] += 1
                cell["sum"] += us
        if fresh:
            snap = {"metrics": {"rt_request_critical_path_us": {
                "type": "histogram",
                "boundaries": list(self._CP_BOUNDS),
                "samples": [{"tags": {"stage": st}, **cell}
                            for st, cell in cells.items()],
            }}}
            try:
                self.kvstore.put("metrics", "gcs", pickle.dumps(snap),
                                 overwrite=True, journal=False)
                # direct kvstore puts bypass rpc_kv_put's rollup hook
                self.rollups.ingest("gcs", snap)
            except Exception:
                log.debug("trace metrics publish failed", exc_info=True)

    def _latency_sweep(self) -> None:
        """ns="latency" retention (cfg.latency_retention_s): windows a
        dead worker last published live forever otherwise — an idle
        long-lived cluster accumulates one leftover window per departed
        worker. Keys re-put recently stay; the rest are deleted."""
        keep_s = self.cfg.latency_retention_s
        if keep_s <= 0:
            return
        now = time.monotonic()
        try:
            keys = self.kvstore.keys("latency", "")
        except Exception:
            return
        for k in keys:
            touched = self._latency_touched.get(k)
            if touched is None:
                # first sight (e.g. GCS restart): start the clock now
                self._latency_touched[k] = now
            elif now - touched > keep_s:
                self.kvstore.delete("latency", k)
                self._latency_touched.pop(k, None)
        # drop stamps for keys already gone
        live = set(keys)
        for k in list(self._latency_touched):
            if k not in live:
                self._latency_touched.pop(k, None)

    # -------------------------------------------------------------- lifecycle
    def _on_disconnect(self, conn):
        for subs in self.subs.values():
            subs.discard(conn)
        node_id = self.raylet_conns.pop(conn, None)
        if node_id is not None:
            self._bg.spawn(self._mark_node_dead(node_id, "raylet disconnected"))

    async def _health_loop(self):
        cfg = self.cfg
        while not self._stopping:
            await asyncio.sleep(cfg.health_check_period_s)
            now = time.monotonic()
            deadline = cfg.health_check_period_s * cfg.health_check_failure_threshold
            for info in list(self.nodes.values()):
                if info.alive and now - info.last_heartbeat > deadline:
                    await self._mark_node_dead(info.node_id, "health check timeout")
            # reconciler safety net: kick any PENDING/RESCHEDULING pg
            # with no drive pass in flight (event kicks cover the common
            # cases; this rescues passes that gave up mid-churn)
            self._kick_pgs()
            # ledger audit (every ~10 ticks): cross-check each live
            # node's held bundles against the pgs table. The backstop
            # for a commit that LANDED raylet-side but whose ack was
            # lost (dead pooled socket, raylet alive): the bundle is
            # committed, so the raylet's own lease GC will never
            # reclaim it — only this sweep (or a re-register) can
            self._audit_tick = getattr(self, "_audit_tick", 0) + 1
            if self._audit_tick % 10 == 0:
                for info in list(self.nodes.values()):
                    if info.alive:
                        await self._audit_node_bundles(info)
                self._latency_sweep()
            # trace critical-path pass over quiesced traces (cheap: only
            # traces that stopped growing since the last tick)
            if self.traces:
                self._trace_metrics_tick()
            # restored ALIVE actors whose node never re-registered after a
            # GCS restart are dead, not merely unobserved
            restored_at = getattr(self, "_restored_at", None)
            if restored_at is not None and now - restored_at > deadline:
                self._restored_at = None
                alive_nodes = {nid for nid, n in self.nodes.items() if n.alive}
                for info in list(self.actors.values()):
                    if info.state == ALIVE and info.node_id not in alive_nodes:
                        await self._on_actor_failure(
                            info, "node lost across GCS restart"
                        )
                # restored CREATED pgs with bundles on nodes that never
                # came back reschedule exactly like a live node death
                for pg in list(self.pgs.values()):
                    if pg.state != "CREATED":
                        continue
                    lost = pg.lost_indices(alive_nodes)
                    if lost:
                        await self._reschedule_lost(
                            pg, lost, "node lost across GCS restart")

    def _restore(self):
        """Recover durable tables (ref role: GCS FT via the Redis store
        client, src/ray/gcs/gcs_server/store_client/redis_store_client.cc
        — there every table op journals through Redis). KV bytes were
        already recovered by the native engine at open (snapshot +
        CRC-checked WAL replay, torn tail truncated); this replays the
        Python-side table ops: the snapshot's pickled table blob, then
        every journaled op newer than it. Volatile state (node registry,
        metrics) is rebuilt by re-registration."""
        import pickle as _p

        if not self.persist_path:
            return
        recovered_ops = []
        legacy_migrated = False
        for rec in self.kvstore.recovered_aux_records():
            try:
                op = _p.loads(rec)
            except Exception:
                continue  # CRC passed but unpicklable (version skew): skip
            if op[0] == "legacy_migrated":
                legacy_migrated = True
            recovered_ops.append(op)
        if not self.kvstore.had_snapshot and not legacy_migrated:
            # No native snapshot and no positive migration-complete
            # sentinel: either a fresh cluster, the first start after the
            # engine swap, or a crash MID-migration (some legacy ops
            # journaled, sentinel absent). Re-run the migration — its puts
            # are idempotent (overwrite=False defers to already-migrated
            # native state), so a partial previous pass can never be
            # silently dropped nor clobber what it already wrote.
            # Known narrow edge: a migration completed by a PRE-sentinel
            # build also lands here (records, no sentinel) and re-puts
            # legacy keys that native kvdels since removed — absent delete
            # tombstones the two states are indistinguishable. The window
            # is ~1s: migration marks dirty and the persist loop writes a
            # native snapshot (had_snapshot → skip) on its next tick.
            self._restore_legacy()
        aux = self.kvstore.recovered_snapshot_aux()
        if aux:
            try:
                snap = _p.loads(aux)
                self.job_counter = snap.get("job_counter", 0)
                self.actors = snap.get("actors", {})
                self.named_actors = snap.get("named_actors", {})
                self.pgs = snap.get("pgs", {})
            except Exception:
                # unreadable table blob: KV still recovered
                log.debug("snapshot aux blob unreadable", exc_info=True)
        for op in recovered_ops:
            kind = op[0]
            if kind == "job":
                self.job_counter = max(self.job_counter, op[1])
            elif kind == "actor":
                self.actors[op[1].actor_id] = op[1]
            elif kind == "name":
                self.named_actors[op[1]] = op[2]
            elif kind == "namedel":
                self.named_actors.pop(op[1], None)
            elif kind == "pg":
                self.pgs[op[1].pg_id] = op[1]
        self._restored_at = time.monotonic()

    def _restore_legacy(self):
        """Migration from the pre-native persistence format (a whole-state
        pickle snapshot + [u32 len][pickle(op)] WAL). The native engine
        rejects the old magic and sidelines an unparseable WAL as
        .wal.legacy; this reads both and re-journals EVERY loaded op into
        the native WAL, so acknowledged old-format writes are durable
        immediately — not only after the first snapshot tick.

        Crash-safe: a ("legacy_migrated",) sentinel aux record journals
        once BOTH legacy sources migrated fully — and before the legacy
        WAL file is deleted — and _restore re-runs this whole pass while
        the sentinel is absent. Re-runs are idempotent: the first write of
        each key this pass uses overwrite=False (native state — what an
        interrupted earlier pass already migrated — wins), while later
        legacy ops on a key this pass already wrote use overwrite=True so
        the legacy log's own ordering is preserved."""
        import pickle as _p
        import struct as _s

        state_loaded = False
        snap_ok = False   # snapshot portion fully migrated (or absent)
        wal_ok = False    # WAL portion fully migrated (or absent)
        touched: set[tuple[str, str]] = set()  # (ns, key) written this pass

        def kv_migrate(ns: str, k: str, v) -> None:
            self.kvstore.put(ns, k, v, overwrite=(ns, k) in touched,
                             journal=True)
            touched.add((ns, k))

        try:
            if os.path.exists(self.persist_path):
                with open(self.persist_path, "rb") as f:
                    head = f.read(2)
                if head[:1] == b"\x80":  # pickle protocol marker
                    with open(self.persist_path, "rb") as f:
                        snap = _p.load(f)
                    for ns, table in snap.get("kv", {}).items():
                        if ns == "metrics":
                            continue
                        for k, v in table.items():
                            kv_migrate(ns, k, v)
                    self.job_counter = snap.get("job_counter", 0)
                    self.actors = snap.get("actors", {})
                    self.named_actors = snap.get("named_actors", {})
                    self.pgs = snap.get("pgs", {})
                    if self.job_counter:
                        self.kvstore.journal_aux(
                            _p.dumps(("job", self.job_counter)))
                    for info in self.actors.values():
                        self.kvstore.journal_aux(_p.dumps(("actor", info)))
                    for name, aid in self.named_actors.items():
                        self.kvstore.journal_aux(_p.dumps(("name", name, aid)))
                    for pg in self.pgs.values():
                        self.kvstore.journal_aux(_p.dumps(("pg", pg)))
                    state_loaded = True
            snap_ok = True  # absent, non-legacy, or fully journaled
        except Exception:
            # partial migration: sentinel stays absent, next start re-runs
            log.debug("legacy snapshot migration incomplete", exc_info=True)
        legacy_wal = self.persist_path + ".wal.legacy"
        try:
            if not os.path.exists(legacy_wal):
                wal_ok = True
            else:
                with open(legacy_wal, "rb") as f:
                    buf = f.read()
                off = 0
                while off + 4 <= len(buf):
                    (ln,) = _s.unpack_from("<I", buf, off)
                    if off + 4 + ln > len(buf):
                        break
                    try:
                        op = _p.loads(buf[off + 4:off + 4 + ln])
                    except Exception:
                        break  # new-format bytes sidelined by a torn head
                    off += 4 + ln
                    kind = op[0]
                    if kind == "kvput":
                        kv_migrate(op[1], op[2], op[3])
                    elif kind == "kvdel":
                        self.kvstore.delete(op[1], op[2], journal=True)
                        touched.add((op[1], op[2]))
                    elif kind == "job":
                        self.job_counter = max(self.job_counter, op[1])
                        self.kvstore.journal_aux(_p.dumps(op))
                    elif kind == "actor":
                        self.actors[op[1].actor_id] = op[1]
                        self.kvstore.journal_aux(_p.dumps(op))
                    elif kind == "name":
                        self.named_actors[op[1]] = op[2]
                        self.kvstore.journal_aux(_p.dumps(op))
                    elif kind == "namedel":
                        self.named_actors.pop(op[1], None)
                        self.kvstore.journal_aux(_p.dumps(op))
                    elif kind == "pg":
                        self.pgs[op[1].pg_id] = op[1]
                        self.kvstore.journal_aux(_p.dumps(op))
                    state_loaded = True
                wal_ok = True
        except Exception:
            log.debug("legacy WAL migration incomplete", exc_info=True)
        if snap_ok and wal_ok:
            try:
                # Migration-complete sentinel: journaled only when BOTH
                # legacy sources migrated fully, and BEFORE the legacy WAL
                # is deleted — a crash anywhere earlier leaves the sentinel
                # absent (next start re-runs the idempotent migration with
                # every source still on disk); a crash between sentinel
                # and remove only leaks an already-migrated file.
                self.kvstore.journal_aux(_p.dumps(("legacy_migrated",)))
                if os.path.exists(legacy_wal):
                    # every replayed op is in the native WAL (flushed per
                    # append): the legacy copy is redundant
                    os.remove(legacy_wal)
            except (OSError, TypeError):
                pass  # sentinel retry next start; sources still on disk
        if state_loaded:
            self.mark_dirty()  # next snapshot converts to native format

    # ------------------------------------------------------------- WAL
    # Table ops journal as opaque (pickled) aux records through the
    # native engine's WAL — one binary log, CRC-framed, shared with the
    # KV ops the engine journals itself (gcs_core.cc).
    def _journal(self, op: tuple) -> None:
        import pickle as _p

        if chaos.ENABLED:
            # "gcs.wal_append", table-op flavor: an `error` action raises
            # out of the mutation handler mid-flight — the un-acked,
            # un-journaled write the WAL recovery tests replay against
            chaos.point("gcs.wal_append", kind=op[0])
        try:
            self.kvstore.journal_aux(_p.dumps(op))
        except (_p.PicklingError, TypeError, AttributeError):
            # unpicklable table entry: this aux record is skipped but the
            # periodic snapshot still covers the mutation
            log.debug("WAL aux journal skipped for %r", op[0],
                      exc_info=True)
        self.mark_dirty()
        self._kick_sync()

    def mark_dirty(self):
        self._dirty = True

    async def _persist_loop(self):
        import pickle as _p

        while not self._stopping:
            await asyncio.sleep(1.0)
            if not self._dirty:
                continue
            self._dirty = False
            if not self._write_snapshot():
                self._dirty = True  # keep trying: the write failed

    def _write_snapshot(self) -> bool:
        """Native atomic snapshot: KV bytes stream from C++, the Python
        tables ride as the pickled aux blob; the WAL truncates inside the
        same native call."""
        import pickle as _p

        try:
            aux = _p.dumps({
                "job_counter": self.job_counter,
                "actors": dict(self.actors),
                "named_actors": dict(self.named_actors),
                "pgs": dict(self.pgs),
            })
            return self.kvstore.snapshot(aux, skip_ns="metrics")
        except Exception:
            return False

    async def start(self) -> tuple[str, int]:
        self._restore()
        addr = await self.server.start()
        # reconcile restored actor state (ref: GCS FT actor reconstruction):
        # PENDING actors lost their scheduling coroutine with the old
        # process — reschedule them now
        for info in self.actors.values():
            if info.state == PENDING:
                self._bg.spawn(self._schedule_actor(info))
        self._bg.spawn(self._health_loop())
        if self.persist_path:
            self._bg.spawn(self._persist_loop())
        return addr

    async def stop(self):
        self._stopping = True
        await self._bg.cancel_all()
        for conn in list(self._node_conns.values()):
            try:
                await conn.close()
            except (rpc.RpcError, OSError):
                pass  # pooled socket already dead
        self._node_conns.clear()
        if self.persist_path and self._dirty:
            self._write_snapshot()  # final flush: acknowledged writes survive
        await self.server.stop()
        self.kvstore.close()


def _fits(req: dict, avail: dict) -> bool:
    return all(avail.get(k, 0.0) >= v - 1e-9 for k, v in req.items())


def _fits_all(bundles: list[dict], avail: dict) -> bool:
    total: dict[str, float] = {}
    for b in bundles:
        for k, v in b.items():
            total[k] = total.get(k, 0.0) + v
    return _fits(total, avail)


def main():
    import argparse

    chaos.maybe_arm()  # fault schedule rides the serialized config

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--address-file", default=None)
    parser.add_argument("--persist", default=None,
                        help="snapshot file for durable tables (GCS FT)")
    args = parser.parse_args()

    # run the server from the CANONICAL module: under `python -m` this
    # file executes as __main__, and anything pickled with __main__-homed
    # classes (ActorInfo/PlacementGroupInfo in the WAL, most importantly)
    # would be unloadable by any normally-importing process
    import ray_tpu.core.gcs as _canonical

    async def run():
        gcs = _canonical.GcsServer(
            args.host, args.port, persist_path=args.persist)
        host, port = await gcs.start()
        line = f"{host}:{port}"
        if args.address_file:
            tmp = args.address_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(line)
            os.replace(tmp, args.address_file)
        print(f"GCS listening on {line}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()

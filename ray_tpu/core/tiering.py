"""Arena-owner registry for cooperative spill (memory tiering).

The raylet's spill monitor can only see *unreferenced* sealed objects;
the planes that matter under pressure — the radix prefix cache, the
sharded plane, decode-pool staging — hold live borrows on every page
they cache, so those bytes were previously unreclaimable short of
eviction (and eviction means re-prefill / re-seal). This module is the
handshake that fixes that: an arena owner registers a *provider*
callback that can name cold referenced objects it is willing to trade
to tier-1, and the raylet asks through the owner process's core client
(``rpc_arena_spill_candidates``) when the arena crosses the spill
threshold. After the raylet writes the bytes out it reports back
(``rpc_arena_spilled``) so the owner can stamp the manifest entry's
``(tier, path)`` leg.

Everything here is process-local state plus two thin client RPC routes;
the actual byte movement stays in ``core/raylet.py``.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Callable

log = logging.getLogger(__name__)

# tier legs on manifest entries (KVPageEntry / ShardEntry)
TIER_SHM = 0   # bytes sealed in the local shm arena
TIER_DISK = 1  # bytes in the raylet's spill directory; restore on read

# provider: (need_bytes, cold_after_s) -> [(oid_binary, nbytes), ...]
Provider = Callable[[int, float], list]

_lock = threading.Lock()
_providers: dict[str, Provider] = {}
# spilled-notification sinks: name -> (oid_binary, path) -> None
_sinks: dict[str, Callable[[bytes, str], None]] = {}
_attached: set[int] = set()  # id(core) of clients already raylet-registered
# arena byte accounting (observability plane): name -> () -> {"bytes": n,
# "capacity": n | 0}. Sampled on the core client's flush timer into the
# rt_arena_* gauges; peaks tracked per arena so watermark HISTORY (not an
# instantaneous read) reaches the rollup plane and the dashboard.
_stats: dict[str, Callable[[], dict]] = {}
_watermarks: dict[str, "object"] = {}  # name -> WatermarkTracker


def register_arena_owner(name: str, provider: Provider,
                         on_spilled: Callable[[bytes, str], None]
                         | None = None) -> None:
    """Register a cold-candidate provider under ``name`` (idempotent —
    re-registering replaces). Registration is process-local and lazy:
    the raylet learns this process can provide candidates the first time
    a core client is attached (see :func:`attach_core`)."""
    with _lock:
        _providers[name] = provider
        if on_spilled is not None:
            _sinks[name] = on_spilled
    _try_attach()


def unregister_arena_owner(name: str) -> None:
    with _lock:
        _providers.pop(name, None)
        _sinks.pop(name, None)
        _stats.pop(name, None)
        _watermarks.pop(name, None)


def register_arena_stats(name: str,
                         stats: Callable[[], dict]) -> None:
    """Register a byte-accounting callback for arena ``name``:
    ``() -> {"bytes": live, "capacity": total | 0}``. Idempotent; the
    arena's watermark tracker starts fresh on (re)registration."""
    from ray_tpu.core.metrics_store import WatermarkTracker

    with _lock:
        _stats[name] = stats
        _watermarks[name] = WatermarkTracker()


def sample_arenas(now: float | None = None) -> dict[str, dict]:
    """Sample every registered arena's live bytes into its watermark
    tracker and return ``{name: {bytes, peak, recent_peak, capacity}}``.
    Called from the core client's 1/s flush (gauge publish) and usable
    anywhere history beats an instantaneous read. A failing provider is
    skipped, never raised."""
    with _lock:
        items = [(n, _stats[n], _watermarks[n]) for n in _stats]
    out = {}
    for name, fn, wm in items:
        try:
            st = fn() or {}
            wm.note(float(st.get("bytes", 0)), now)
        except Exception:
            log.debug("arena stats provider %s failed", name, exc_info=True)
            continue
        out[name] = {"bytes": wm.live, "peak": wm.peak,
                     "recent_peak": wm.recent_peak(10.0, now),
                     "capacity": float(st.get("capacity", 0) or 0)}
    return out


def arena_watermark(name: str):
    """The arena's WatermarkTracker (None when unregistered) — spill
    policy and tests read peak history through this."""
    with _lock:
        return _watermarks.get(name)


def collect_candidates(need: int, cold_after_s: float) -> list[dict]:
    """All providers' cold candidates, oldest-first, enough to cover
    ``need`` bytes (providers may return less; never more than asked)."""
    with _lock:
        provs = list(_providers.values())
    out, got = [], 0
    for p in provs:
        try:
            cands = p(max(0, need - got), cold_after_s)
        except Exception:
            continue
        for oid, nbytes in cands:
            out.append({"object_id": oid, "nbytes": int(nbytes)})
            got += int(nbytes)
        if got >= need > 0:
            break
    return out


def notify_spilled(spilled: list[dict]) -> None:
    """Raylet reported these objects now live on tier-1; fan out to every
    owner so manifests can stamp their (tier, path) legs."""
    with _lock:
        sinks = list(_sinks.values())
    for item in spilled:
        oid, path = item.get("object_id"), item.get("path", "")
        for sink in sinks:
            try:
                sink(oid, path)
            except Exception:
                log.debug("spill sink failed", exc_info=True)


def attach_core(core) -> None:
    """Tell ``core``'s raylet that this process serves spill candidates
    (once per client). Safe to call before the client is connected —
    registration is retried from register_arena_owner call sites."""
    if core is None or getattr(core, "raylet", None) is None:
        return
    with _lock:
        if id(core) in _attached:
            return
        if not _providers:
            return
        _attached.add(id(core))
    try:
        core.register_spill_provider()
    except Exception:
        with _lock:
            _attached.discard(id(core))


def _try_attach() -> None:
    try:
        from ray_tpu.core import api

        attach_core(getattr(api, "_core", None))
    except Exception:
        log.debug("spill-provider attach failed", exc_info=True)


def _reset_for_tests() -> None:
    with _lock:
        _providers.clear()
        _sinks.clear()
        _attached.clear()
        _stats.clear()
        _watermarks.clear()


class ColdTracker:
    """Cold-set bookkeeping for a plane that seals arena objects it keeps
    referenced (shard plane seals, decode-pool staging pages). Tracks
    (seal time, nbytes, entry) per oid and serves as both the provider
    (cold, tier-0, still-alive entries) and the spilled sink (stamps the
    entry's tier leg). Entries are held by weakref so the tracker never
    extends an object's lifetime past its manifest."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        # oid binary -> (ts, nbytes, weakref(entry))
        self._items: dict[bytes, tuple] = {}
        register_arena_owner(name, self.candidates, self.on_spilled)
        register_arena_stats(name, lambda: {"bytes": self.total_bytes()})

    def total_bytes(self) -> int:
        """Tier-0 bytes this plane still holds referenced (dead entries
        and already-spilled ones don't count against the arena)."""
        total = 0
        with self._lock:
            items = list(self._items.values())
        for _ts, nbytes, eref in items:
            entry = eref()
            if entry is not None and \
                    getattr(entry, "tier", TIER_SHM) == TIER_SHM:
                total += nbytes
        return total

    def track(self, oid: bytes, nbytes: int, entry) -> None:
        with self._lock:
            self._items[oid] = (time.monotonic(), int(nbytes),
                                weakref.ref(entry))

    def untrack(self, oid: bytes) -> None:
        with self._lock:
            self._items.pop(oid, None)

    def candidates(self, need: int, cold_after_s: float) -> list:
        now = time.monotonic()
        out, got, dead = [], 0, []
        with self._lock:
            items = sorted(self._items.items(), key=lambda kv: kv[1][0])
        for oid, (ts, nbytes, eref) in items:
            entry = eref()
            if entry is None:
                dead.append(oid)
                continue
            if getattr(entry, "tier", TIER_SHM) != TIER_SHM:
                continue
            if now - ts < cold_after_s:
                continue
            out.append((oid, nbytes))
            got += nbytes
            if got >= need > 0:
                break
        if dead:
            with self._lock:
                for oid in dead:
                    self._items.pop(oid, None)
        return out

    def on_spilled(self, oid: bytes, path: str) -> None:
        with self._lock:
            item = self._items.get(oid)
        if item is None:
            return
        entry = item[2]()
        if entry is not None:
            entry.tier = TIER_DISK
            entry.spill_path = path

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

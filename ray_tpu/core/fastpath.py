"""Steady-state task-submission fast path over native shm rings.

The role of the reference's C++ steady-state submit loop (ref:
src/ray/core_worker/transport/normal_task_submitter.cc:28 lease-cached
PushTask pipelining, core_worker.cc:2500 SubmitTask): once a lease is
cached for a scheduling key, pushing one more task of the same shape and
reading its reply should never touch an event loop, a socket, or a
serialized RPC frame on either side.

Mechanics: at lease grant the driver creates a :class:`RingPair` — one
POSIX shm segment holding two SPSC byte rings (native side:
_native/src/ring.cc) — and tells the worker to attach. Eligible submits
(plain sync function, inline args, single return, default scheduling)
pickle ``(task_id, func_id, args, kwargs)`` into the submit ring straight
from the calling thread; the worker's pump thread pops batches, executes
on the worker's single task-executor thread, and pushes packed results
into the reply ring; a driver reader thread completes blocking ``get()``s
directly and trickles the results onto the event loop for everything else
(memory-store entries, task events, wait()).

The reply lane is the COMPLETION fast lane, mirroring the submit lane's
semantics in the opposite direction: results at or below
``fastpath_inline_result_max`` ride inside the completion record (no
object-store put, no location registration); larger ones seal into the
node's shm arena and the record carries the size, priming the owner's
location cache at completion time. The worker pump merges records that
arrive mid-batch into one reply frame and pushes with partial-push
semantics — whole records land as they fit, and once the ring has stayed
full past ``fastpath_reply_spill_ms`` the remainder spills to the driver
over RPC (``rpc_fast_result``), so a stalled driver can never wedge task
execution.

Anything that doesn't fit — generators, tasks with options, worker death
mid-flight — falls back to the ordinary RPC path, which stays the single
source of truth for scheduling semantics.

Cross-node (protocol 2.0), the SAME packed records ride the node tunnel
(core/tunnel.py): one persistent multiplexed connection per node pair
carries coalesced frames of these records instead of per-call pickled
RPC specs, with ``FastLane`` reused verbatim driver-side — a
:class:`~ray_tpu.core.tunnel.TunnelRing` duck-types the ring face, so
tx coalescing (txbuf + adaptive defer + linger), seq-matched
out-of-order replies and break-lane recovery are one code path for shm
and tunnel lanes. Payloads above ``tunnel_inline_max`` do not ride the
tunnel: see :class:`TunnelArgRef` and :func:`pack_shm_desc`.

Actor lanes (protocol 1.8) ride the same rings with three extras: records
carry a per-lane call sequence number, replies echo it, and completions
may stream back OUT of submission order — async-actor methods execute on
the worker's event loop and reply as each finishes, so ring order is the
per-caller FIFO *dispatch* invariant, not a completion invariant. Calls
the lane cannot carry (a not-yet-local ObjectRef argument, a generator
method, a per-call options override) fall back to the RPC path per CALL:
the driver drains the lane's in-flight records first (FIFO across the
mixed stream) and the lane resumes fast service afterwards.
"""

from __future__ import annotations

import ctypes
import pickle
import struct
import threading

from ray_tpu import _native
from ray_tpu.devtools import chaos
from ray_tpu.utils import serialization

SUB = 0  # driver -> worker (task records)
REP = 1  # worker -> driver (result records)

# pop-side staging buffer size; every record pushed into a ring MUST fit
# here or the consumer can never drain it (rt_ring_pop_batch -> kTooBig)
POP_BUF_BYTES = 1 << 20

# reply status codes
OK = 0        # payload = packed inline value
OK_SHM = 1    # result sealed into the node's shm arena under the return
#               oid; payload = <Q size (primes the owner's location cache
#               at completion time; empty payload = size unknown)
ERR = 2       # payload = pickled TaskError
NEED_SLOW = 3  # func not executable on the fast path: resubmit via RPC
# streaming chunk statuses (wire 2.3): carried by "G" chunk records ONLY
# (pack_chunk) — never by terminal reply records, so the four statuses
# above keep their exact meaning for every non-stream consumer
CHUNK = 4      # payload = one packed yielded item
CHUNK_SHM = 5  # oversized item sealed in the node arena under the
#                chunk's derived oid (return index chunk_seq + 1 of the
#                call's task id — index 0 stays the terminal reply's);
#                payload = pack_shm_size / pack_shm_desc like OK_SHM

_ST_OK = 0
_ST_TIMEOUT = -4
_ST_CLOSED = -7
_ST_TOOBIG = -9

# rt_ring_stats field order (ring.cc RingStats)
RING_STAT_FIELDS = (
    "push_ops", "push_bytes", "push_records", "pop_ops", "pop_bytes",
    "pop_records", "producer_waits", "consumer_waits", "wake_signals",
    "spin_hits", "partial_pushes", "peak_used",
)


class RingClosed(Exception):
    pass


class RingPair:
    """ctypes face of one rt_ring pair (see ring.cc for the protocol).

    Lifecycle safety: any thread may call :meth:`close` (it only flips the
    in-shm closed flags and wakes sleepers), but :meth:`close_pair` unmaps
    the segment — it marks the handle dead, wakes every blocked call, and
    waits for in-flight C calls to drain before the munmap, so no thread
    can touch freed memory."""

    def __init__(self, name: str, handle: int, owner: bool):
        self.name = name
        self._h = handle
        self._owner = owner
        self._lib = _native.get_lib()
        self._popbuf = ctypes.create_string_buffer(POP_BUF_BYTES)
        self._dead = threading.Event()  # close_pair started
        self._inflight = 0
        self._cv = threading.Condition()

    @classmethod
    def create(cls, name: str, cap_each: int) -> "RingPair":
        lib = _native.get_lib()
        h = lib.rt_ring_pair_create(name.encode(), cap_each)
        if not h:
            raise OSError(f"could not create ring shm {name}")
        return cls(name, h, owner=True)

    @classmethod
    def open(cls, name: str) -> "RingPair":
        lib = _native.get_lib()
        h = lib.rt_ring_pair_open(name.encode())
        if not h:
            raise OSError(f"could not open ring shm {name}")
        return cls(name, h, owner=False)

    def _enter(self) -> bool:
        with self._cv:
            if self._dead.is_set():
                return False
            self._inflight += 1
            return True

    def _exit(self) -> None:
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    def push(self, which: int, payload: bytes, timeout_ms: int = -1) -> int:
        """Returns a _ST_* status; never raises on full/closed."""
        if chaos.ENABLED:
            st = _chaos_push(which, len(payload))
            if st:
                return st
        if not self._enter():
            return _ST_CLOSED
        try:
            return self._lib.rt_ring_push(
                self._h, which, payload, len(payload), timeout_ms)
        finally:
            self._exit()

    def push_raw(self, which: int, framed: bytes, timeout_ms: int = -1) -> int:
        if chaos.ENABLED:
            st = _chaos_push(which, len(framed))
            if st:
                return st
        if not self._enter():
            return _ST_CLOSED
        try:
            return self._lib.rt_ring_push_raw(
                self._h, which, framed, len(framed), timeout_ms)
        finally:
            self._exit()

    def push_batch(self, which: int, framed: bytes, timeout_ms: int = 0) -> int:
        """Push as many whole records of a pre-framed buffer as currently
        fit (waiting up to timeout_ms for the first): returns bytes
        consumed (>= 0) or a negative _ST_* status. One lock round and at
        most one consumer wake for the whole batch — the native half of
        the coalesced flush."""
        if chaos.ENABLED:
            st = _chaos_push(which, len(framed))
            if st:
                return 0 if st == _ST_TIMEOUT else st
        if not self._enter():
            return _ST_CLOSED
        try:
            return self._lib.rt_ring_push_batch(
                self._h, which, framed, len(framed), timeout_ms)
        finally:
            self._exit()

    def pop_batch(self, which: int, timeout_ms: int) -> list[bytes] | None:
        """None once closed AND drained; [] on timeout."""
        if not self._enter():
            return None
        try:
            n = self._lib.rt_ring_pop_batch(
                self._h, which,
                ctypes.cast(self._popbuf, ctypes.POINTER(ctypes.c_uint8)),
                len(self._popbuf), timeout_ms)
        finally:
            self._exit()
        if n == _ST_CLOSED or n == _ST_TOOBIG:
            # closed, or a record that can never fit the pop buffer:
            # either way this ring is done — the caller breaks the lane
            # and recovers over RPC
            return None
        if n <= 0:
            return []
        return unframe(self._popbuf.raw[:n])

    def pending(self, which: int) -> int:
        if not self._enter():
            return 0
        try:
            return self._lib.rt_ring_pending(self._h, which)
        finally:
            self._exit()

    def stats(self, which: int) -> dict[str, int] | None:
        """One direction's shared-memory stats block (ring.cc RingStats),
        read straight out of the mapped segment — both sides of the ring
        see identical numbers, so the driver's metrics flush covers the
        worker's half too. None once the pair is dead."""
        if not self._enter():
            return None
        try:
            out = (ctypes.c_uint64 * len(RING_STAT_FIELDS))()
            n = self._lib.rt_ring_stats(
                self._h, which,
                ctypes.cast(out, ctypes.POINTER(ctypes.c_uint64)), len(out))
        finally:
            self._exit()
        return {name: int(out[i])
                for i, name in enumerate(RING_STAT_FIELDS[:n])}

    def close(self, which: int) -> None:
        if not self._enter():
            return
        try:
            self._lib.rt_ring_close(self._h, which)
        finally:
            self._exit()

    def is_closed(self, which: int) -> bool:
        if not self._enter():
            return True
        try:
            return bool(self._lib.rt_ring_closed(self._h, which))
        finally:
            self._exit()

    def close_pair(self) -> None:
        with self._cv:
            if self._dead.is_set():
                return
            self._dead.set()
        # wake every blocked call (handle still mapped), then wait for the
        # in-flight count to drain before unmapping
        self._lib.rt_ring_close(self._h, SUB)
        self._lib.rt_ring_close(self._h, REP)
        with self._cv:
            while self._inflight > 0:
                self._cv.wait(1.0)
        self._lib.rt_ring_pair_close(self._h)
        if self._owner:
            self._lib.rt_ring_pair_destroy(self.name.encode())

    def unlink(self) -> None:
        """Remove the shm name now (mapping stays valid until close_pair);
        idempotent, so teardown can't leak /dev/shm entries even if the
        owning reader thread never gets to run again."""
        self._lib.rt_ring_pair_destroy(self.name.encode())


def _chaos_push(which: int, nbytes: int) -> int:
    """Chaos verdict for one ring push ("ring.push" fault point): 0 =
    proceed; drop maps to the ring-full status (caller retries from the
    consumed prefix / spills to RPC), error maps to closed (caller
    breaks the lane and recovers over RPC) — both recoveries the rings
    already promise, now reachable on demand."""
    try:
        act = chaos.point("ring.push", which=which, bytes=nbytes)
    except chaos.ChaosError:
        return _ST_CLOSED  # pushes report status codes, never raise
    if act is not None and act.kind == "drop":
        return _ST_TIMEOUT
    return 0  # duplicate/corrupt are not meaningful for ring pushes


def frame(records: list[bytes]) -> bytes:
    """[u32 len][payload] per record, 8-aligned — rt_ring_push_raw format."""
    parts = []
    for rec in records:
        pad = (-(4 + len(rec))) % 8
        parts.append(struct.pack("<I", len(rec)) + rec + b"\x00" * pad)
    return b"".join(parts)


def frame_one(rec: bytes) -> bytes:
    """frame([rec]) without the list round-trip (submit hot path)."""
    pad = (-(4 + len(rec))) % 8
    return struct.pack("<I", len(rec)) + rec + b"\x00" * pad


def unframe(buf: bytes) -> list[bytes]:
    out = []
    off = 0
    n = len(buf)
    while off + 4 <= n:
        (ln,) = struct.unpack_from("<I", buf, off)
        out.append(buf[off + 4:off + 4 + ln])
        off += (4 + ln + 7) & ~7
    return out


_SIMPLE = (int, float, str, bytes, bool, type(None))


def _simple(x, depth: int = 2) -> bool:
    if isinstance(x, _SIMPLE):
        return True
    if depth:
        if isinstance(x, (list, tuple)):
            return all(_simple(v, depth - 1) for v in x)
        if isinstance(x, dict):
            return all(isinstance(k, _SIMPLE) and _simple(v, depth - 1)
                       for k, v in x.items())
    return False


def pack_task(task_id: bytes, func_id: bytes, args, kwargs,
              t_ns: int = 0, trace: bytes = b"") -> bytes:
    """Two-tier arg encoding. Simple immutables take the C pickler (the
    submission hot path — a Python-level reducer hook here measured ~2x on
    the whole bench); anything else goes through serialization.pack, whose
    rules match the RPC path: functions/classes from __main__ or test
    modules ship by value, jax arrays devolve to numpy, nested ObjectRefs
    run the borrow protocol. Plain pickle would encode those by reference
    and silently mean something else on the worker.

    ``t_ns`` (protocol 1.7, flight recorder) is the driver's
    ``perf_counter_ns`` at submit: CLOCK_MONOTONIC is system-wide on
    Linux and fast lanes are same-node, so the worker's pop-time minus
    this stamp IS the submit-ring hop. Stamped records use the "Q"/"R"
    prefixes; un-stamped "P"/"S" stay decodable (recorder off).

    ``trace`` (protocol 2.1) is a packed 25-byte trace leg
    (tracing.pack_ctx) riding behind the stamp: its presence is flagged
    by TRACE_BIT in the stamp's top bit (perf_counter_ns can't reach
    bit 63 for ~292 years, so the bit is free), which keeps unsampled
    records byte-identical to 1.7 ones. A traced record always uses the
    stamped prefixes — t_ns=0 still decodes as "no recorder stamp"."""
    if _simple(args) and (not kwargs or _simple(kwargs)):
        body = pickle.dumps((task_id, func_id, args, kwargs), protocol=5)
        if trace:
            return (b"Q" + struct.pack("<Q", t_ns | TRACE_BIT) + trace
                    + body)
        if t_ns:
            return b"Q" + struct.pack("<Q", t_ns) + body
        return b"P" + body
    body = serialization.pack((task_id, func_id, args, kwargs))
    if trace:
        return b"R" + struct.pack("<Q", t_ns | TRACE_BIT) + trace + body
    if t_ns:
        return b"R" + struct.pack("<Q", t_ns) + body
    return b"S" + body


def unpack_task(rec: bytes):
    """-> (task_id, func_id, args, kwargs, t_submit_ns, trace) — t 0
    when the record carries no recorder stamp, trace b"" when it
    carries no trace leg (decode with tracing.unpack_ctx)."""
    kind = rec[:1]
    if kind == b"P":
        return (*pickle.loads(rec[1:]), 0, b"")
    if kind == b"S":
        return (*serialization.unpack(rec[1:]), 0, b"")
    (t_ns,) = struct.unpack_from("<Q", rec, 1)
    off = 9
    trace = b""
    if t_ns & TRACE_BIT:
        t_ns &= ~TRACE_BIT
        trace = rec[off:off + TRACE_LEN]
        off += TRACE_LEN
    if kind == b"Q":
        return (*pickle.loads(rec[off:]), t_ns, trace)
    return (*serialization.unpack(rec[off:]), t_ns, trace)


# reply-status flag bit: a 16-byte stage stamp follows the header
# (protocol 1.7; kept ≤ 16 bytes so inline results stay under the
# fastpath_inline_result_max threshold budget)
STAMPED = 0x100
# reply-status flag bit (protocol 1.8): a 4-byte per-call sequence number
# follows the header (after the stamp when both are present). Actor-lane
# replies echo the seq the submit record carried, so the driver can match
# completions that stream back OUT of submission order (async actors
# reply as each method finishes) while ring order stays the per-caller
# FIFO *dispatch* invariant.
SEQED = 0x200
# reply-status flag bit (protocol 2.1): a 25-byte trace leg
# (tracing.pack_ctx: <16s trace_id><8s span_id><B sampled>) follows the
# header after the stamp/seq legs. Traced replies ECHO the submit
# record's context, so the driver's reply-apply can stamp the wire-level
# call span for untracked (serve fast-lane) calls without a lookup.
TRACED = 0x400
# record-side trace flag (protocol 2.1): bit 63 of the u64 t_submit
# field of "Q"/"R"/"A"/"C" records — set = a 25-byte trace leg follows
# the record header. Mirrored as kRecordTraceCtxBit in rt_wire.h and
# machine-checked by tests/test_wire_schema.py.
TRACE_BIT = 1 << 63
TRACE_LEN = 25  # struct <16s8sB> — tracing._WIRE
_STAMP = struct.Struct("<IIQ")  # ring_ns (sat), deser_ns (sat), exec_ns
_SEQ = struct.Struct("<I")
_AHDR = struct.Struct("<IQ")    # actor record header: seq, t_submit_ns
_U32_MAX = 0xFFFFFFFF


def pack_actor_task(task_id: bytes, mkey: bytes, args, kwargs,
                    t_ns: int, seq: int, trace: bytes = b"") -> bytes:
    """Actor-lane task record (protocol 1.8). Same two-tier arg encoding
    as :func:`pack_task` ("A" = C pickler, "C" = serialization.pack), but
    the header always carries the per-lane call sequence number plus the
    submit stamp (0 when the recorder is off) — the seq is what lets
    async-actor completions stream back out of ring order while the
    driver still accounts each call exactly once. ``trace`` (2.1) rides
    behind the header, flagged by TRACE_BIT exactly like task records."""
    if _simple(args) and (not kwargs or _simple(kwargs)):
        body = pickle.dumps((task_id, mkey, args, kwargs), protocol=5)
        if trace:
            return b"A" + _AHDR.pack(seq, t_ns | TRACE_BIT) + trace + body
        return b"A" + _AHDR.pack(seq, t_ns) + body
    body = serialization.pack((task_id, mkey, args, kwargs))
    if trace:
        return b"C" + _AHDR.pack(seq, t_ns | TRACE_BIT) + trace + body
    return b"C" + _AHDR.pack(seq, t_ns) + body


def unpack_actor_task(rec: bytes):
    """-> (task_id, mkey, args, kwargs, t_submit_ns, seq, trace).
    Pre-1.8 actor records ("P"/"S"/"Q"/"R") decode with seq=None;
    untraced records decode with trace=b""."""
    kind = rec[:1]
    if kind in (b"A", b"C"):
        seq, t_ns = _AHDR.unpack_from(rec, 1)
        off = 13
        trace = b""
        if t_ns & TRACE_BIT:
            t_ns &= ~TRACE_BIT
            trace = rec[off:off + TRACE_LEN]
            off += TRACE_LEN
        if kind == b"A":
            return (*pickle.loads(rec[off:]), t_ns, seq, trace)
        return (*serialization.unpack(rec[off:]), t_ns, seq, trace)
    t = unpack_task(rec)
    return (*t[:5], None, t[5])


def pack_stamp(ring_ns: int, deser_ns: int, exec_ns: int) -> bytes:
    """Worker-side stage stamp: submit-ring hop (pop - t_submit),
    deserialize (pop -> user-function entry, includes function load and
    exec-mutex acquire), and exec (the user function). The reply hop is
    derived driver-side as total - (ring + deser + exec). Fast path
    packs unclamped (every per-task nanosecond counts — see the bench's
    recorder_overhead_us budget); only out-of-range values (a >4.3s
    ring stall, a clock anomaly) pay the clamping retry."""
    try:
        return _STAMP.pack(ring_ns, deser_ns, exec_ns)
    except struct.error:
        return _STAMP.pack(min(max(ring_ns, 0), _U32_MAX),
                           min(max(deser_ns, 0), _U32_MAX),
                           max(exec_ns, 0))


def unpack_stamp(stamp: bytes) -> tuple[int, int, int]:
    return _STAMP.unpack(stamp)


def pack_reply(task_id: bytes, status: int, payload: bytes,
               stamp: bytes = b"", seq: int | None = None,
               trace: bytes = b"") -> bytes:
    if stamp:
        status |= STAMPED
    tail = stamp
    if seq is not None:
        status |= SEQED
        tail += _SEQ.pack(seq)
    if trace:
        status |= TRACED
        tail += trace
    if tail:
        return struct.pack("<16sI", task_id, status) + tail + payload
    return struct.pack("<16sI", task_id, status) + payload


def unpack_reply(rec: bytes):
    """-> (task_id, status, payload, stamp | None, seq | None, trace) —
    trace b"" unless the reply echoes a submit record's trace leg."""
    task_id, status = struct.unpack_from("<16sI", rec)
    off = 20
    stamp = None
    seq = None
    trace = b""
    if status & STAMPED:
        stamp = rec[off:off + 16]
        off += 16
    if status & SEQED:
        (seq,) = _SEQ.unpack_from(rec, off)
        off += 4
    if status & TRACED:
        trace = rec[off:off + TRACE_LEN]
        off += TRACE_LEN
    return (task_id, status & ~(STAMPED | SEQED | TRACED), rec[off:],
            stamp, seq, trace)


_CHDR = struct.Struct("<16sI")  # chunk body header: task_id, status


def pack_chunk(task_id: bytes, status: int, payload: bytes,
               chunk_seq: int, t_ns: int = 0, trace: bytes = b"") -> bytes:
    """Streaming chunk record ("G", wire 2.3): one seq-matched partial
    completion of a stream-called generator method, flushed per yielded
    item. The header is byte-for-byte the "A"/"C" shape —
    ``<u32 chunk_seq><u64 t_emit_ns>`` with the same TRACE_BIT trace leg
    — so the rings and tunnels order chunks with the machinery they
    already have; the seq slot carries the PER-STREAM chunk index
    (monotonic from 0), not the lane call seq. The body is the reply
    shape: ``<16s task_id><u32 status>`` + payload, status CHUNK
    (inline packed item) or CHUNK_SHM (shm size/desc — the item sealed
    under return index chunk_seq + 1 of the call's task id). The
    stream's END is NOT a "G" record: an ordinary :func:`pack_reply`
    terminal (OK + pack_stream_fin / ERR) closes it on the lane's
    normal seq machinery. An unsampled chunk (no trace leg) is
    byte-identical to one packed before tracing existed — the leg costs
    nothing unless the request is sampled."""
    if trace:
        return (b"G" + _AHDR.pack(chunk_seq, t_ns | TRACE_BIT) + trace
                + _CHDR.pack(task_id, status) + payload)
    return (b"G" + _AHDR.pack(chunk_seq, t_ns)
            + _CHDR.pack(task_id, status) + payload)


def unpack_chunk(rec: bytes):
    """-> (task_id, status, payload, chunk_seq, t_emit_ns, trace), or
    None when ``rec`` is not a well-formed "G" record. Callers that
    share a stream with reply records probe with this FIRST and fall
    back to :func:`unpack_reply` — a reply's leading task-id byte may
    collide with 'G', so chunk routing additionally requires the parsed
    task id to match a registered stream (16 random bytes: a stray
    match is ~2^-128)."""
    if rec[:1] != b"G" or len(rec) < 33:
        return None
    chunk_seq, t_ns = _AHDR.unpack_from(rec, 1)
    off = 13
    trace = b""
    if t_ns & TRACE_BIT:
        t_ns &= ~TRACE_BIT
        trace = rec[off:off + TRACE_LEN]
        off += TRACE_LEN
    if len(rec) < off + 20:
        return None
    task_id, status = _CHDR.unpack_from(rec, off)
    return task_id, status, rec[off + 20:], chunk_seq, t_ns, trace


def pack_stream_fin(nchunks: int) -> bytes:
    """Terminal OK payload of a stream call: the total chunk count, so
    the driver's sink can assert every chunk landed (ring + RPC-spill
    interleavings may reorder; the sink reorders by chunk_seq and the
    count closes the stream exactly once)."""
    return _SEQ.pack(nchunks)


def unpack_stream_fin(payload: bytes) -> int | None:
    if len(payload) >= 4:
        return _SEQ.unpack_from(payload)[0]
    return None


def pack_shm_size(size: int) -> bytes:
    """OK_SHM payload: the sealed result's byte size."""
    return struct.pack("<Q", size)


def unpack_shm_size(payload: bytes) -> int | None:
    if len(payload) >= 8:
        return struct.unpack_from("<Q", payload)[0]
    return None


def pack_shm_desc(size: int, node: bytes) -> bytes:
    """OK_SHM payload for CROSS-NODE completions (protocol 2.0, tunnel
    lanes): ``<Q size><16s holder node id>`` — the record itself is the
    location registration, so the owner primes its cache with the node
    that actually sealed the result and the later get() pulls straight
    from it (descriptors, not payloads, ride the tunnel)."""
    return struct.pack("<Q16s", size, node)


def unpack_shm_desc(payload: bytes) -> tuple[int | None, bytes | None]:
    """-> (size, holder node id | None). Plain size payloads (same-node
    shm rings, pre-2.0 records) decode with node None."""
    if len(payload) >= 24:
        size, node = struct.unpack_from("<Q16s", payload)
        return size, node
    if len(payload) >= 8:
        return struct.unpack_from("<Q", payload)[0], None
    return None, None


class TunnelArgRef:
    """Descriptor for one oversized tunnel-record argument (protocol
    2.0): the value was sealed into the SENDER's local shm arena and the
    record carries only ``(oid, owner address, holder node, nbytes)`` —
    the receiver adopts the bytes via one batched ``pull_objects`` round
    trip (core/tunnel.py). The sender pins the minted ref until the
    call's reply lands, so the sealed copy cannot be freed mid-pull."""

    __slots__ = ("oid", "owner", "node", "nbytes")

    def __init__(self, oid: bytes, owner, node: bytes | None, nbytes: int):
        self.oid = oid
        self.owner = tuple(owner) if owner else None
        self.node = node
        self.nbytes = nbytes

    def __reduce__(self):
        return (TunnelArgRef, (self.oid, self.owner, self.node,
                               self.nbytes))

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"TunnelArgRef({self.oid.hex()[:12]}, {self.nbytes}B)"


class FastLane:
    """Driver-side state for one leased worker's ring (submission side).

    ``inflight`` maps task_id -> the light lineage tuple
    ``(func_id, args, kwargs, resources, max_retries, name)`` needed to
    rebuild a full spec if the worker dies. Guarded by the CoreClient's
    fast condition variable; the reader thread pops entries as replies
    arrive.
    """

    __slots__ = ("ring", "worker", "key", "inflight", "broken", "reader",
                 "return_armed", "rx_lock", "user_wants", "resume_evt",
                 "retired", "txbuf", "txbytes", "txlock", "seq_counter",
                 "next_seq", "done_seq", "ooo_replies", "drain_evt",
                 "drain_waiters", "methods", "flush_max_records",
                 "flush_max_bytes")

    def __init__(self, ring: RingPair, worker, key):
        self.ring = ring
        self.worker = worker
        self.key = key
        self.inflight: dict = {}
        self.broken = False
        self.reader: threading.Thread | None = None
        self.return_armed = False  # one idle lease-return watcher at a time
        # actor lanes (protocol 1.8): per-lane call sequence — drawn
        # lock-free (itertools.count: next() is GIL-atomic) at submit,
        # echoed in every reply so completions may stream back out of
        # submission order (async actors). done_seq is the highest seq
        # applied; ooo_replies counts replies that arrived below it (the
        # out-of-order evidence, surfaced by
        # CoreClient.fast_actor_lane_stats for tests and the bench);
        # next_seq is the advisory mirror those stats read.
        import itertools

        self.seq_counter = itertools.count()
        self.next_seq = 0
        self.done_seq = -1
        self.ooo_replies = 0
        # RPC-fallback drain barrier: an asyncio.Event (created on the
        # loop at attach) set whenever ``inflight`` empties WHILE a
        # slow-path call waits on it (drain_waiters > 0 — the gate keeps
        # the loop self-pipe wake OFF the pure-ring round trip, where it
        # measured ~25% of the whole sync call). The actor pump awaits
        # the event before dispatching a slow-path call, replacing the
        # old 1ms busy-poll (the RT013 shape).
        self.drain_evt = None
        self.drain_waiters = 0
        # worker-shipped method eligibility table (attach reply, 1.8):
        # name -> (verdict, concurrency_group); None = unknown (pre-1.8
        # worker), in which case the worker-side NEED_SLOW stays the gate
        self.methods = None
        # Coalesced submit flush: framed records buffered here during a
        # burst ride ONE rt_ring_push_batch (one ring lock round + at most
        # one futex wake) instead of a push per record. Every buffered
        # record is already registered in ``inflight``, so break-lane
        # recovery treats buffered and in-ring records identically.
        self.txbuf: list = []
        self.txbytes = 0
        self.txlock = threading.Lock()
        # per-lane coalescing caps: None = the config defaults. Tunnel
        # lanes widen these (a network frame amortizes over far more
        # records than a same-node ring wake does).
        self.flush_max_records = None
        self.flush_max_bytes = None
        # actor lanes: permanent RPC downgrade. Since 1.8 this fires ONLY
        # on a worker-side NEED_SLOW (a method missing from the shipped
        # eligibility table — dynamically added, or a stale table);
        # ineligible ARGUMENTS and ineligible methods the driver can see
        # coming fall back per CALL instead. In-flight records still drain.
        self.retired = False
        # reply-ring consumer election: a blocking get() steals consumption
        # from the sweeper thread (one thread hop fewer per result); the
        # sweeper parks while user_wants is recent.
        self.rx_lock = threading.Lock()
        self.user_wants = 0.0  # monotonic ts of the last stealing get()
        self.resume_evt = threading.Event()

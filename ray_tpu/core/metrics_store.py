"""GCS-side timeseries rollup plane (the cluster's metric history).

TPU-native equivalent of the reference stats aggregation layer (ref:
src/ray/stats/ + the dashboard's time-series export behind
``export_*.proto``): workers keep piggybacking registry snapshots into
the volatile ns="metrics" KV on the task-event flush timer, and the GCS
— which already sees every one of those puts in ``rpc_kv_put`` — folds
them into ring-buffered fixed windows here instead of only remembering
"now". Aggregation stays off the worker hot path (the Dapper/Monarch
shape the flight recorder already follows): the rollup cost rides the
1/s flush, never a task submit.

Three ideas, all restart-safe:

* **Counter deltas.** Snapshots carry monotonic cumulatives. Per
  (source, metric, tag-cell) the store remembers the last cumulative and
  windows the *delta*; a reset (worker restarted, registry re-created —
  the new cumulative is below the old) contributes the new cumulative
  itself, clamped >= 0, so a restart can never produce a negative rate.
* **Mergeable histograms.** Snapshots carry fixed-boundary bucket
  counts; deltas merge bucket-wise across sources, and quantiles come
  from the merged buckets (prometheus-style interpolation), so a
  cluster-wide p99 needs no raw samples.
* **Derived ratios.** Rate-of-two-counters series (spec-decode
  acceptance, serve SLO breach fraction) are computed slot-by-slot from
  their numerator/denominator deltas — boundary-free and correct across
  restarts, unlike averaging per-process lifetime gauges.

Windows exist at three resolutions (1s/10s/60s) with bounded retention;
``window()`` picks the finest resolution whose retention covers the
request. Everything in this module is plain dict/float state guarded by
one lock — no asyncio, no RPC — so tests can drive it directly.
"""
from __future__ import annotations

import threading
import time

# (resolution seconds, retained slots): 1s for 3 min, 10s for 1 h,
# 60s for 4 h — bounded memory no matter how long the cluster lives.
RESOLUTIONS = (1, 10, 60)
RETENTION_SLOTS = {1: 180, 10: 360, 60: 240}

# Derived ratio series: name -> (numerator counter, denominator counter).
# Registered by default so `state.metric_window("llm_spec_accept_rate",
# 10)` works with no extra wiring anywhere else.
DEFAULT_RATIOS = {
    "llm_spec_accept_rate": ("rt_llm_spec_accepted_total",
                             "rt_llm_spec_proposed_total"),
    "serve_slo_breach_fraction": ("rt_serve_slo_breaches_total",
                                  "rt_serve_requests_total"),
}


def bucket_quantile(boundaries, counts, q: float) -> float:
    """Quantile from fixed-boundary bucket counts (prometheus
    histogram_quantile shape: linear interpolation inside the bucket,
    the +Inf bucket clamps to the last finite boundary)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c > 0:
            lo = boundaries[i - 1] if i > 0 else 0.0
            hi = boundaries[i] if i < len(boundaries) else boundaries[-1]
            frac = (rank - (cum - c)) / c
            return lo + (hi - lo) * frac
    return float(boundaries[-1]) if boundaries else 0.0


def _tag_key(tags: dict | None) -> tuple:
    return tuple(sorted((tags or {}).items()))


class RollupStore:
    """Multi-resolution windowed rollups over per-source registry
    snapshots. One instance lives on the GCS; ``ingest`` is called from
    ``rpc_kv_put`` for every ns="metrics" publish (source = the kv key:
    worker hex, "gcs", "raylet.<node>")."""

    def __init__(self, ratios: dict | None = None):
        self._lock = threading.Lock()
        self._types: dict[str, str] = {}
        self._bounds: dict[str, tuple] = {}
        # (source, name, tagkey) -> last counter cumulative
        self._last_counter: dict[tuple, float] = {}
        # (source, name, tagkey) -> (bucket counts tuple, sum)
        self._last_hist: dict[tuple, tuple] = {}
        # res -> slot epoch -> name -> tagkey -> cell
        #   counter cell: float delta          gauge cell: {source: value}
        #   histogram cell: {"counts": [...], "sum": float}
        self._slots: dict[int, dict[int, dict]] = {r: {} for r in RESOLUTIONS}
        self._ratios = dict(DEFAULT_RATIOS if ratios is None else ratios)
        # source -> last ingest wall ts (stale-source GC for the delta maps)
        self._source_seen: dict[str, float] = {}

    # -------------------------------------------------------------- ingest
    def ingest(self, source: str, snap: dict, now: float | None = None):
        """Fold one registry snapshot (``{"metrics": {name: {...}}}``)
        into every resolution's current slot. Arrival-timestamped: slot
        alignment uses the GCS clock, not the publisher's."""
        now = time.time() if now is None else now
        metrics = (snap or {}).get("metrics") or {}
        with self._lock:
            self._source_seen[source] = now
            for name, m in metrics.items():
                kind = m.get("type")
                samples = m.get("samples")
                if kind not in ("counter", "gauge", "histogram") or \
                        samples is None:
                    continue
                self._types[name] = kind
                if kind == "histogram":
                    self._bounds[name] = tuple(m.get("boundaries") or ())
                for s in samples:
                    tkey = _tag_key(s.get("tags"))
                    if kind == "counter":
                        self._ingest_counter(source, name, tkey,
                                             float(s.get("value", 0.0)), now)
                    elif kind == "gauge":
                        self._ingest_gauge(source, name, tkey,
                                           float(s.get("value", 0.0)), now)
                    else:
                        self._ingest_hist(source, name, tkey,
                                          s.get("counts") or [],
                                          float(s.get("sum", 0.0)), now)
            self._evict(now)

    def _cell(self, res: int, now: float, name: str, tkey: tuple,
              default):
        slot = int(now) - int(now) % res
        by_name = self._slots[res].setdefault(slot, {})
        return by_name.setdefault(name, {}).setdefault(tkey, default)

    def _ingest_counter(self, source, name, tkey, cum, now):
        key = (source, name, tkey)
        last = self._last_counter.get(key)
        # restart-safe delta: a reset (cum < last) counts the new
        # cumulative itself — clamped >= 0, never a negative rate
        delta = cum if (last is None or cum < last) else cum - last
        self._last_counter[key] = cum
        if delta <= 0:
            return
        for res in RESOLUTIONS:
            slot = int(now) - int(now) % res
            by_name = self._slots[res].setdefault(slot, {})
            cells = by_name.setdefault(name, {})
            cells[tkey] = cells.get(tkey, 0.0) + delta

    def _ingest_gauge(self, source, name, tkey, value, now):
        for res in RESOLUTIONS:
            cell = self._cell(res, now, name, tkey, None)
            if cell is None:
                slot = int(now) - int(now) % res
                cell = self._slots[res][slot][name][tkey] = {}
            cell[source] = value

    def _ingest_hist(self, source, name, tkey, counts, total, now):
        key = (source, name, tkey)
        cur = tuple(int(c) for c in counts)
        last = self._last_hist.get(key)
        if last is None or len(last[0]) != len(cur) or \
                any(c < p for c, p in zip(cur, last[0])):
            # first sight or reset: the whole cumulative is the delta
            dc, ds = cur, total
        else:
            dc = tuple(c - p for c, p in zip(cur, last[0]))
            ds = max(0.0, total - last[1])
        self._last_hist[key] = (cur, total)
        if not any(dc):
            return
        for res in RESOLUTIONS:
            cell = self._cell(res, now, name, tkey, None)
            if cell is None:
                slot = int(now) - int(now) % res
                cell = self._slots[res][slot][name][tkey] = {
                    "counts": [0] * len(dc), "sum": 0.0}
            if len(cell["counts"]) != len(dc):
                cell["counts"] = [0] * len(dc)
            cell["counts"] = [a + b for a, b in zip(cell["counts"], dc)]
            cell["sum"] += ds

    def _evict(self, now: float):
        for res in RESOLUTIONS:
            floor = (int(now) - int(now) % res) - res * RETENTION_SLOTS[res]
            slots = self._slots[res]
            for slot in [s for s in slots if s < floor]:
                del slots[slot]
        # delta maps for sources gone > 10 min keep no ghosts around
        dead = [s for s, ts in self._source_seen.items() if now - ts > 600.0]
        for s in dead:
            del self._source_seen[s]
            for m in (self._last_counter, self._last_hist):
                for key in [k for k in m if k[0] == s]:
                    del m[key]

    # --------------------------------------------------------------- query
    def _pick_res(self, secs: float) -> int:
        for res in RESOLUTIONS:
            if res * RETENTION_SLOTS[res] >= secs:
                return res
        return RESOLUTIONS[-1]

    def names(self) -> list[dict]:
        with self._lock:
            rows = [{"name": n, "type": t}
                    for n, t in sorted(self._types.items())]
            rows.extend({"name": n, "type": "ratio",
                         "num": num, "den": den}
                        for n, (num, den) in sorted(self._ratios.items()))
        return rows

    def window(self, name: str, secs: float, tags: dict | None = None,
               now: float | None = None) -> dict:
        """Rate/quantile series over the trailing ``secs`` seconds,
        oldest-first, one point per non-empty slot at the finest
        resolution whose retention covers the request. Counter points:
        ``{ts, value (delta), rate}``; gauge points: ``{ts, value}``
        (summed across sources/cells); histogram points: ``{ts, count,
        sum, rate, p50, p90, p99}``; ratio points: ``{ts, value, num,
        den}`` (slots with a zero denominator are skipped)."""
        now = time.time() if now is None else now
        with self._lock:
            ratio = self._ratios.get(name)
            if ratio is not None:
                return self._ratio_window(name, *ratio, secs, tags, now)
            kind = self._types.get(name)
            res = self._pick_res(secs)
            points = []
            if kind is not None:
                tkey = _tag_key(tags) if tags else None
                for slot, cells in self._iter_slots(name, res, secs, now):
                    if tkey is not None:
                        if tkey not in cells:
                            continue
                        picked = [cells[tkey]]
                    else:
                        picked = list(cells.values())
                    pt = self._point(kind, name, slot, res, picked)
                    if pt is not None:
                        points.append(pt)
            return {"name": name, "type": kind, "res": res,
                    "points": points}

    def _iter_slots(self, name, res, secs, now):
        """(slot, tag-cells) for every retained slot of ``name`` inside
        the window, ascending."""
        end = int(now) - int(now) % res
        start = end - (int(secs // res) * res)
        slots = self._slots[res]
        out = []
        for slot in sorted(slots):
            if slot < start or slot > end:
                continue
            cells = slots[slot].get(name)
            if cells:
                out.append((slot, cells))
        return out

    def _point(self, kind, name, slot, res, cells):
        if kind == "counter":
            delta = float(sum(cells))
            return {"ts": slot, "value": delta, "rate": delta / res}
        if kind == "gauge":
            # sum across sources and tag cells: per-arena bytes add up
            # to cluster bytes; filter by tags for one cell's value
            return {"ts": slot,
                    "value": float(sum(sum(c.values()) for c in cells))}
        counts = None
        total = 0.0
        for c in cells:
            if counts is None:
                counts = list(c["counts"])
            else:
                counts = [a + b for a, b in zip(counts, c["counts"])]
            total += c["sum"]
        if not counts:
            return None
        bounds = self._bounds.get(name, ())
        n = sum(counts)
        return {"ts": slot, "count": int(n), "sum": total,
                "rate": n / res,
                "p50": bucket_quantile(bounds, counts, 0.5),
                "p90": bucket_quantile(bounds, counts, 0.9),
                "p99": bucket_quantile(bounds, counts, 0.99)}

    def _ratio_window(self, name, num, den, secs, tags, now):
        res = self._pick_res(secs)
        tkey = _tag_key(tags) if tags else None

        def deltas(metric):
            out = {}
            for slot, cells in self._iter_slots(metric, res, secs, now):
                if tkey is not None:
                    if tkey in cells:
                        out[slot] = float(cells[tkey])
                else:
                    out[slot] = float(sum(cells.values()))
            return out

        nd, dd = deltas(num), deltas(den)
        points = []
        for slot in sorted(dd):
            d = dd[slot]
            if d <= 0:
                continue
            n = nd.get(slot, 0.0)
            points.append({"ts": slot, "value": n / d, "num": n, "den": d})
        return {"name": name, "type": "ratio", "res": res, "points": points}

    def export_rates(self, secs: float = 10.0,
                     now: float | None = None) -> dict:
        """Per-tag-cell trailing rates for every counter plus every
        derived ratio's trailing value — the compact feed
        ``state.prometheus_metrics`` renders as ``:rate10s`` families."""
        now = time.time() if now is None else now
        out: dict[str, dict] = {}
        with self._lock:
            res = self._pick_res(secs)
            for name, kind in self._types.items():
                if kind != "counter":
                    continue
                cells: dict[tuple, float] = {}
                for _slot, by_tag in self._iter_slots(name, res, secs, now):
                    for tkey, delta in by_tag.items():
                        cells[tkey] = cells.get(tkey, 0.0) + float(delta)
                if cells:
                    out[name] = {"type": "counter", "samples": [
                        {"tags": dict(tk), "rate": v / secs}
                        for tk, v in cells.items()]}
            for name, (num, den) in self._ratios.items():
                win = self._ratio_window(name, num, den, secs, None, now)
                pts = win["points"]
                if not pts:
                    continue
                n = sum(p["num"] for p in pts)
                d = sum(p["den"] for p in pts)
                if d > 0:
                    out[name] = {"type": "ratio", "samples": [
                        {"tags": {}, "rate": n / d}]}
        return out


class WatermarkTracker:
    """Live + peak byte watermarks with a short per-second peak ring, so
    consumers (the raylet's spill trigger, the dashboard) read recent
    *history* instead of whatever instant they happened to sample."""

    def __init__(self, ring_slots: int = 120, slot_s: float = 1.0):
        self.slot_s = float(slot_s)
        self.ring_slots = int(ring_slots)
        self.live = 0.0
        self.peak = 0.0  # lifetime high-water
        self._ring: dict[int, float] = {}  # slot epoch -> max live seen

    def note(self, live_bytes: float, now: float | None = None):
        now = time.time() if now is None else now
        self.live = float(live_bytes)
        if self.live > self.peak:
            self.peak = self.live
        slot = int(now / self.slot_s)
        cur = self._ring.get(slot)
        if cur is None or self.live > cur:
            self._ring[slot] = self.live
        floor = slot - self.ring_slots
        for s in [s for s in self._ring if s < floor]:
            del self._ring[s]

    def recent_peak(self, secs: float, now: float | None = None) -> float:
        """Max live bytes noted inside the trailing ``secs`` (includes
        the current live value — a window with no samples is just now)."""
        now = time.time() if now is None else now
        floor = int((now - secs) / self.slot_s)
        vals = [v for s, v in self._ring.items() if s >= floor]
        return max(vals) if vals else self.live

    def series(self, secs: float, now: float | None = None) -> list[tuple]:
        now = time.time() if now is None else now
        floor = int((now - secs) / self.slot_s)
        return sorted((s * self.slot_s, v) for s, v in self._ring.items()
                      if s >= floor)

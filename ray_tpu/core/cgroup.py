"""Cgroup manager: per-worker memory isolation.

The reference's "physical execution mode" (ref: src/ray/common/cgroup/
cgroup_manager.h, cgroup_setup.h, README.md layout
/sys/fs/cgroup/ray_node_<id>/application) puts each worker in a cgroup so
a task's memory cap is enforced by the kernel, not just advised by the
memory monitor. Same shape here:

    rt_node_<id>/              node root
        application/           all workers (leaf cgroups per worker)
            w_<worker_id>/     memory.max = the lease's "memory" resource

Drivers: cgroup v2 (unified hierarchy), cgroup v1 (memory controller),
and a Fake driver recording operations for tests (ref:
fake_cgroup_setup.h). Real kernels need write access to the hierarchy;
when unavailable the manager reports unsupported and the raylet skips
isolation (advisory memory monitor still runs).
"""

from __future__ import annotations

import os


class CgroupError(Exception):
    pass


class CgroupV2Driver:
    """Unified hierarchy: /sys/fs/cgroup with cgroup.controllers present."""

    def __init__(self, base: str = "/sys/fs/cgroup"):
        self.base = base

    def supported(self) -> bool:
        return (
            os.path.isfile(os.path.join(self.base, "cgroup.controllers"))
            and os.access(self.base, os.W_OK)
        )

    def create(self, path: str, mem_limit: int | None = None) -> None:
        full = os.path.join(self.base, path)
        os.makedirs(full, exist_ok=True)
        # v2: a child only gets a memory.max file if its PARENT delegates
        # the controller. Never write the group's own subtree_control —
        # that would trip the no-internal-process rule for leaves.
        parent = os.path.dirname(full) or self.base
        try:
            with open(os.path.join(parent, "cgroup.subtree_control"), "w") as f:
                f.write("+memory")
        except OSError:
            pass  # root policy may refuse: delegation is best-effort
        if mem_limit is not None:
            self.set_limit(path, mem_limit)

    def set_limit(self, path: str, mem_limit: int | None) -> None:
        value = "max" if mem_limit is None else str(int(mem_limit))
        with open(os.path.join(self.base, path, "memory.max"), "w") as f:
            f.write(value)

    def add_pid(self, path: str, pid: int) -> None:
        with open(os.path.join(self.base, path, "cgroup.procs"), "w") as f:
            f.write(str(pid))

    def remove(self, path: str) -> bool:
        full = os.path.join(self.base, path)
        try:
            os.rmdir(full)
            return True
        except FileNotFoundError:
            return True
        except OSError:
            return not os.path.isdir(full)  # EBUSY: procs still exiting

    def current_usage(self, path: str) -> int | None:
        try:
            with open(os.path.join(self.base, path, "memory.current")) as f:
                return int(f.read())
        except OSError:
            return None


class CgroupV1Driver:
    """Legacy split hierarchy: memory controller at /sys/fs/cgroup/memory."""

    def __init__(self, base: str = "/sys/fs/cgroup/memory"):
        self.base = base

    def supported(self) -> bool:
        return (
            os.path.isfile(os.path.join(self.base, "memory.limit_in_bytes"))
            and os.access(self.base, os.W_OK)
        )

    def create(self, path: str, mem_limit: int | None = None) -> None:
        full = os.path.join(self.base, path)
        os.makedirs(full, exist_ok=True)
        if mem_limit is not None:
            self.set_limit(path, mem_limit)

    def set_limit(self, path: str, mem_limit: int | None) -> None:
        value = "-1" if mem_limit is None else str(int(mem_limit))
        with open(os.path.join(self.base, path, "memory.limit_in_bytes"), "w") as f:
            f.write(value)

    def add_pid(self, path: str, pid: int) -> None:
        with open(os.path.join(self.base, path, "cgroup.procs"), "w") as f:
            f.write(str(pid))

    def remove(self, path: str) -> bool:
        full = os.path.join(self.base, path)
        try:
            os.rmdir(full)
            return True
        except FileNotFoundError:
            return True
        except OSError:
            return not os.path.isdir(full)

    def current_usage(self, path: str) -> int | None:
        try:
            with open(os.path.join(self.base, path,
                                   "memory.usage_in_bytes")) as f:
                return int(f.read())
        except OSError:
            return None


class FakeCgroupDriver:
    """In-memory driver for tests (ref: fake_cgroup_setup.h): records every
    create/add_pid/remove so assertions can check the lifecycle without a
    writable kernel hierarchy."""

    def __init__(self):
        self.cgroups: dict[str, dict] = {}  # path -> {"limit":, "pids": set}
        self.removed: list[str] = []

    def supported(self) -> bool:
        return True

    def create(self, path: str, mem_limit: int | None = None) -> None:
        self.cgroups.setdefault(path, {"limit": None, "pids": set()})
        if mem_limit is not None:
            self.cgroups[path]["limit"] = mem_limit

    def set_limit(self, path: str, mem_limit: int | None) -> None:
        if path not in self.cgroups:
            raise CgroupError(f"no cgroup {path}")
        self.cgroups[path]["limit"] = mem_limit

    def add_pid(self, path: str, pid: int) -> None:
        if path not in self.cgroups:
            raise CgroupError(f"no cgroup {path}")
        self.cgroups[path]["pids"].add(pid)

    def remove(self, path: str) -> bool:
        self.cgroups.pop(path, None)
        self.removed.append(path)
        return True

    def current_usage(self, path: str) -> int | None:
        return 0 if path in self.cgroups else None


def detect_driver():
    """Best available real driver, or None (isolation unsupported)."""
    for driver in (CgroupV2Driver(), CgroupV1Driver()):
        if driver.supported():
            return driver
    return None


class CgroupManager:
    """Node-scoped cgroup tree with per-worker leaves.

    Created by the raylet when worker isolation is enabled; the "memory"
    resource on a lease becomes the worker's kernel memory cap (ref:
    cgroup_manager.h per-task memory caps).
    """

    def __init__(self, node_id_hex: str, driver=None):
        self.driver = driver
        self.root = f"rt_node_{node_id_hex[:12]}"
        self.app = f"{self.root}/application"
        self._workers: dict[str, str] = {}  # worker_id -> leaf path
        if self.driver is not None:
            try:
                self.driver.create(self.root, None)
                self.driver.create(self.app, None)
            except (OSError, CgroupError):
                # detect_driver's W_OK probe can pass in containers where
                # mkdir is still refused: degrade to advisory-only instead
                # of failing raylet startup
                self.driver = None

    @property
    def enabled(self) -> bool:
        return self.driver is not None

    def isolate_worker(self, worker_id_hex: str, pid: int,
                       mem_limit: int | None) -> bool:
        """Place a worker in its leaf cgroup with an optional memory cap."""
        if not self.enabled:
            return False
        leaf = f"{self.app}/w_{worker_id_hex[:12]}"
        try:
            self.driver.create(leaf, mem_limit)
            self.driver.add_pid(leaf, pid)
        except (OSError, CgroupError):
            self.driver.remove(leaf)  # partial create must not leak the dir
            return False
        self._workers[worker_id_hex] = leaf
        return True

    def set_limit(self, worker_id_hex: str, mem_limit: int | None) -> bool:
        """Update (or with None, RESET) a worker's memory cap — a recycled
        worker must not inherit the previous lease's limit."""
        leaf = self._workers.get(worker_id_hex)
        if leaf is None or not self.enabled:
            return False
        try:
            self.driver.set_limit(leaf, mem_limit)
        except (OSError, CgroupError):
            return False
        return True

    def release_worker(self, worker_id_hex: str) -> None:
        leaf = self._workers.get(worker_id_hex)
        if leaf is None:
            return
        if not self.enabled or self.driver.remove(leaf):
            self._workers.pop(worker_id_hex, None)
        # else: leaf still busy (proc exiting); kept for a later retry

    def worker_usage(self, worker_id_hex: str) -> int | None:
        leaf = self._workers.get(worker_id_hex)
        if leaf is None or not self.enabled:
            return None
        return self.driver.current_usage(leaf)

    def teardown(self) -> bool:
        """Remove all leaves + the node tree; False if anything is still
        busy (caller may retry after the owning processes exit)."""
        if not self.enabled:
            return True
        for wid in list(self._workers):
            self.release_worker(wid)
        ok = not self._workers
        ok = self.driver.remove(self.app) and ok
        return self.driver.remove(self.root) and ok

"""Worker process: executes tasks and hosts actors.

Equivalent of the reference's worker side: task receiver + execution
callback (ref: src/ray/core_worker/transport/task_receiver.h:50,
python/ray/_raylet.pyx:1731 execute_task, worker.py:955 main_loop).

Threading model: the asyncio loop owns all sockets and stays responsive
(serving owner-object requests, accepting new pushes) while user code runs
on executor threads — sync tasks/actors on a single-thread executor
(per-caller FIFO preserved: one connection per caller x in-order dispatch x
one execution thread), async actors directly on the loop, actors with
max_concurrency > 1 on a wider pool (ref: concurrency groups,
concurrency_group_manager.cc).

Executing a task also runs a full CoreClient, so tasks can submit nested
tasks, put objects, and get borrowed refs — same as the reference where
every worker embeds a CoreWorker.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import logging
import os
import sys
import time
import traceback

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle

from ray_tpu.config import get_config
from ray_tpu.core.core_client import CoreClient, _pack_bytes
from ray_tpu.core.ref import ObjectRef, TaskError
from ray_tpu.devtools import chaos
from ray_tpu.utils import metrics, rpc, serialization
from ray_tpu.utils.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID

log = logging.getLogger(__name__)

_current_worker = None  # set by Worker.start(): runtime_context introspection
_profiler = None  # RT_WORKER_PROFILE_DIR cProfile, dumped on exit_worker


class Worker:
    def __init__(self):
        self.cfg = get_config()
        self.worker_id = WorkerID.from_hex(os.environ["RT_WORKER_ID"])
        self.raylet_address = (
            os.environ["RT_RAYLET_HOST"],
            int(os.environ["RT_RAYLET_PORT"]),
        )
        self.gcs_address = (os.environ["RT_GCS_HOST"], int(os.environ["RT_GCS_PORT"]))
        self.node_id = NodeID.from_hex(os.environ["RT_NODE_ID"])
        self.core: CoreClient | None = None
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rt-exec"
        )
        self._func_cache: dict[bytes, object] = {}
        # actor state (a worker hosts at most one actor, like the reference)
        self.actor_instance = None
        self.actor_id: ActorID | None = None
        # keyed by the live Connection object (cleaned on disconnect): an
        # id()-keyed map could collide after CPython address reuse
        self._seq_gates: dict[object, dict] = {}
        self._exit_requested = False
        # normal-task ids currently executing, for exact-identity force
        # cancellation (cancel_if_current) — never holds actor task ids
        self._current_tasks: set = set()
        # actor concurrency groups (populated by rpc_create_actor)
        self._method_groups: dict = {}
        self._group_execs: dict = {}
        self._group_sems: dict = {}
        # fast-path rings attached by drivers (see core/fastpath.py)
        self._fast_rings: list = []
        # node-tunnel lanes attached through the raylet (core/tunnel.py):
        # lane id -> state dict; records arrive as rpc_tunnel_records
        # frames and replies coalesce back per loop tick
        self._tunnel_lanes: dict[int, dict] = {}
        self._tunnel_tasks: set = set()  # strong holds on dispatched execs
        # cached connections to drivers for result-ring spill (rpc_fast_result)
        self._spill_conns: dict[tuple, object] = {}
        # one-task-per-worker guard for NORMAL tasks: ring-pump inline
        # execution and RPC-path executor runs must never run two tasks
        # at once on this one-CPU lease (the driver's quiet-lane worker
        # preference is best-effort, not an exclusion). Uncontended in
        # the pure-ring and pure-RPC steady states.
        import threading as _threading

        self._exec_mutex = _threading.Lock()
        # actor-lane W_TASK sampling counter (see _fast_actor_exec_batch)
        self._rec_wt_n = 0
        # wire tracing (utils/tracing.py): cached like the driver's
        # _trace_on — gates the per-record UNSAMPLED suppression (head
        # sampling is per request: an untraced record under tracing-on
        # means the submitter decided unsampled, so nested .remote()
        # calls from its user code must not re-draw a fresh root)
        self._trace_on = bool(self.cfg.tracing_enabled)

    async def start(self):
        # Apply the forced-CPU backend (tests / single-chip hosts) BEFORE
        # anything can touch jax: unpacking a jax-array argument triggers
        # device_put, and an unconfigured worker would try to initialize
        # the axon TPU backend — hanging on the single tunneled chip the
        # driver already holds.
        from ray_tpu.utils.device import configure_jax

        configure_jax()
        # Flight recorder: shm-file-backed under the session tree so the
        # raylet can dump our last-N stage events into the death report
        # after a SIGKILL (no RT_SESSION -> manually spawned: stay
        # anonymous/in-memory).
        from ray_tpu.utils import recorder as _recorder

        if self.cfg.recorder_enabled:
            session = os.environ.get("RT_SESSION")
            _recorder.init_process_recorder(
                _recorder.worker_recorder_path(
                    self.cfg.temp_dir, session, self.worker_id.hex())
                if session else None)
        # register on the CANONICAL module: under `python -m` this file
        # also exists as `__main__`, and runtime_context imports
        # ray_tpu.core.worker — the two must agree
        import ray_tpu.core.worker as _canonical

        _canonical._current_worker = self
        self.core = CoreClient(loop=asyncio.get_running_loop())
        # adopt the raylet-assigned identity: runtime_context.worker_id and
        # the raylet's spawn bookkeeping (log files, chip grants, kills)
        # must name the same worker
        self.core.worker_id = self.worker_id
        # the worker's own server doubles as the task receiver
        self.core.server.add_routes(self)
        self.core.server.on_disconnect = lambda conn: self._seq_gates.pop(conn, None)
        await self.core.connect(self.gcs_address, self.raylet_address)
        # user code in tasks (ray_tpu.get/put/remote, actor handles) must hit
        # THIS core, not bootstrap a fresh cluster (ref: worker.py global_worker)
        from ray_tpu.core import api

        api._core = self.core
        raylet = self.core.raylet
        await raylet.call(
            "worker_ready",
            {"worker_id": self.worker_id.hex(), "address": self.core.address, "pid": os.getpid()},
        )
        # if the raylet connection drops, the node is gone: exit
        asyncio.get_running_loop().create_task(self._watch_raylet())

    async def _watch_raylet(self):
        while True:
            await asyncio.sleep(1.0)
            if self.core.raylet._closed:
                os._exit(0)

    # ------------------------------------------------------------ execution
    def _apply_accel_env(self, chips):
        """Apply the lease's TPU chip assignment (TPU_VISIBLE_CHIPS +
        bounds) before any user code can initialize jax (ref: worker-side
        accelerator env setup, _private/worker.py set_visible_accelerator_ids
        path). The assignment rides in on the first task/actor push — TPU
        workers are single-assignment (the raylet terminates them at lease
        return), so first-write wins."""
        if not chips or getattr(self, "_accel_env_applied", False):
            return
        self._accel_env_applied = True
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        TPUAcceleratorManager.set_current_process_visible_accelerator_ids(chips)

    async def _apply_runtime_env(self, desc):
        """Materialize the task's runtime env before user code runs (ref:
        runtime_env agent role; packages come from the GCS KV). Workers are
        single-env: the first successfully applied env wins for the process
        lifetime (the reference starts dedicated workers per env); a
        failed application is retried by the next task."""
        if not desc:
            return
        if not hasattr(self, "_runtime_env_lock"):
            self._runtime_env_lock = asyncio.Lock()
        async with self._runtime_env_lock:  # concurrent tasks gate here
            if getattr(self, "_runtime_env_applied", False):
                return
            import os as _os
            import tempfile as _tempfile

            from ray_tpu.runtime_env import apply_runtime_env

            cache = _os.path.join(_tempfile.gettempdir(), "ray_tpu", "runtime_envs")
            blobs = {}
            digests = ([] if not desc.get("working_dir") else [desc["working_dir"]])
            digests += list(desc.get("py_modules", []))
            from ray_tpu.runtime_env import plugin_blob_keys

            for d in digests:
                # node-local content-addressed cache first: warm workers on
                # this node skip the package transfer entirely
                if _os.path.exists(_os.path.join(cache, d + ".done")):
                    continue
                blobs[d] = await self.core.gcs.call(
                    "kv_get", {"ns": "runtime_env_packages", "key": d}
                )
            for key in plugin_blob_keys(desc):
                blobs[key] = await self.core.gcs.call(
                    "kv_get", {"ns": "runtime_env_packages", "key": key}
                )
            # off-loop: plugin applies can run pip installs for minutes,
            # and the loop must keep answering pushes and health checks
            await asyncio.get_running_loop().run_in_executor(
                None, apply_runtime_env, desc, lambda k: blobs.get(k))
            self._runtime_env_applied = True  # only after success
            # nested submissions from this worker inherit the env
            self.core.default_runtime_env = desc

    async def _load_function(self, func_id: bytes):
        fn = self._func_cache.get(func_id)
        if fn is not None:
            return fn
        for _ in range(100):  # registration is async on the owner: retry briefly
            blob = await self.core.gcs.call("kv_get", {"ns": "funcs", "key": func_id.hex()})
            if blob is not None:
                fn = cloudpickle.loads(blob)
                self._func_cache[func_id] = fn
                return fn
            await asyncio.sleep(0.05)
        raise TaskError(f"function {func_id.hex()} never appeared in the GCS table")

    async def _fetch_args(self, packed_args):
        out = []
        ref_slots: list[int] = []
        refs: list[ObjectRef] = []
        for a in packed_args:
            tag = a[0]
            if tag == "p":  # plain value
                out.append(a[1])
            elif tag == "v":  # inlined serialized value
                out.append(serialization.unpack(a[1]))
            elif tag == "r":  # ref descriptor: fetch (batched below)
                oid = ObjectID(a[1])
                ref_slots.append(len(out))
                refs.append(ObjectRef(oid, tuple(a[2]) if a[2] else None))
                out.append(None)
            else:
                raise TaskError(f"bad arg tag {tag!r}")
        if refs:
            # one batched get over every ref arg: location priming and
            # the raylet pull coalesce across the whole set (one
            # pull_objects round trip for a multi-arg fetch) instead of
            # one directory lookup + pull RPC per argument
            vals = await self.core.get_async(refs, None)
            for slot, v in zip(ref_slots, vals):
                out[slot] = v
        return out

    async def _store_results(self, task_id, num_returns, values) -> list[dict]:
        if num_returns == 1:
            values = (values,)
        elif num_returns == 0:
            values = ()
        else:
            values = tuple(values)
            if len(values) != num_returns:
                raise TaskError(
                    f"task declared num_returns={num_returns} but returned {len(values)}"
                )
        results = []
        for v in values:
            if inspect.isgenerator(v) or inspect.isasyncgen(v):
                raise TaskError(
                    "task returned a generator; declare it with "
                    "num_returns='streaming' to stream its items"
                )
        for i, v in enumerate(values):
            oid = ObjectID.for_task_return(task_id, i)
            meta, buffers = serialization.dumps_with_buffers(v)
            size = serialization.total_size(meta, buffers)
            if size <= self.cfg.max_inline_object_size:
                results.append({"inline": _pack_bytes(meta, buffers, size)})
            else:
                await self._store_shm_object(oid, meta, buffers)
                # (node, size) primes the owner's location cache at
                # completion time: steady-state get() skips the GCS
                # object-directory lookup entirely
                results.append({"shm": True, "size": size,
                                "node": self.node_id.binary()})
        return results

    async def rpc_cancel_if_current(self, conn, p):
        """Die iff the named task is still executing here. The check runs in
        this process, so a stale force-cancel can never kill a worker that
        finished the task and was reused (ref: CancelTask force_kill)."""
        if p["task_id"] in self._current_tasks:
            loop = asyncio.get_running_loop()
            loop.call_soon(os._exit, 1)  # reply first, then die
            return True
        return False

    # ------------------------------------------------ fast path (shm rings)
    async def rpc_attach_fast_ring(self, conn, p):
        """Driver attaches a shm task ring (see core/fastpath.py). The pump
        thread lives until the ring closes (driver teardown or our exit).
        kind="actor" rings carry actor method calls: the SPSC order IS the
        caller's FIFO *dispatch* order. Sync methods on a strictly serial
        actor execute inline on the pump (zero thread handoffs); async
        methods, threaded actors (max_concurrency > 1) and concurrency-
        group methods are DISPATCHED in ring order to the event loop /
        the right pool and reply as each finishes — out-of-order
        completions, matched driver-side by the per-call seq (1.8).

        The reply ships the actor's init-time method eligibility table so
        the driver routes generator/unknown methods to the RPC path per
        call without a ring round trip."""
        import threading

        from ray_tpu.core import fastpath

        ring = fastpath.RingPair.open(p["name"])
        # the driver's server address: spill target for completion records
        # the result ring cannot absorb (see _fast_spill_replies)
        ring._owner_addr = tuple(p["owner"]) if p.get("owner") else None
        self._fast_rings.append(ring)
        loop = asyncio.get_running_loop()
        if p.get("kind") == "actor":
            table = getattr(self, "_actor_method_table", None)
            # Dispatch-only lanes: whenever two of this actor's methods
            # could legitimately block on each other across threads
            # (thread pool, loop-resident async methods, group pools),
            # inline pump execution could deadlock a rendezvous — every
            # record is dispatched instead, the pump never executes user
            # code. A pure-sync serial actor keeps the zero-handoff
            # inline pump (the measured 1_1_actor_calls_sync win).
            dispatch_only = (
                getattr(self, "_actor_max_concurrency", 1) > 1
                or bool(self._group_execs)
                or any(v[0] == "async" for v in (table or {}).values()))
            # Two-mode pump (inline lanes). HOT: a self-resubmitting job
            # on the actor's single executor thread
            # (_fast_actor_pump_cycle) — ring records execute inline with
            # ZERO thread handoffs (each cross-thread wake costs 60-200us
            # on this class of host, which was most of the sync-call
            # round trip), RPC-path jobs interleave between cycles.
            # PARKED: after ~100ms of silence the cycle chain exits and a
            # dedicated thread blocks on the ring with long timeouts, so
            # an idle actor costs nothing on the executor; the first
            # batch of a new busy period runs via one executor handoff,
            # then the chain goes hot again. Dispatch-only lanes skip the
            # hot chain entirely: the park thread pops and dispatches.
            state = {"downgraded": False, "idle": 0,
                     "parked": threading.Event(),
                     "dispatch_only": dispatch_only}
            t = threading.Thread(
                target=self._fast_actor_park, args=(ring, state),
                name="rt-fastpark", daemon=True)
            t.start()
            return {"ok": True, "methods": table}
        t = threading.Thread(
            target=self._fast_pump, args=(ring, loop),
            name="rt-fastpump", daemon=True)
        t.start()
        return True

    def _fast_push_replies(self, ring, replies) -> int:
        """Deliver completion records with the submit lane's partial-push /
        RPC-spill semantics, mirrored in the opposite direction: push as
        many whole records as currently fit in one native batch call,
        retry the remainder briefly, and once the result ring has stayed
        full past the spill deadline hand the undelivered records to the
        driver over RPC (rpc_fast_result) — a stalled driver must not
        wedge the pump (and with it task execution) behind a full ring.
        Chunked at ~512KB so one frame can never exceed the ring capacity
        or the driver's fixed pop buffer. Returns 0 once every record is
        delivered (ring or spill), or a negative ring status when the
        ring is closed/unusable (the driver's break-lane recovery owns
        whatever did not land)."""
        from ray_tpu.core import fastpath

        spill_s = max(1, self.cfg.fastpath_reply_spill_ms) / 1000.0
        idx = 0
        n = len(replies)
        while idx < n:
            chunk_end = idx
            chunk_bytes = 0
            while chunk_end < n and (chunk_end == idx
                                     or chunk_bytes + len(replies[chunk_end])
                                     <= 512 * 1024):
                chunk_bytes += len(replies[chunk_end])
                chunk_end += 1
            framed = fastpath.frame(replies[idx:chunk_end])
            off = 0
            deadline = time.monotonic() + spill_s
            while off < len(framed):
                took = ring.push_batch(
                    fastpath.REP, framed[off:] if off else framed,
                    timeout_ms=20)
                if took < 0:
                    return took
                off += took
                if off < len(framed) and time.monotonic() >= deadline:
                    # whole records already in the ring stay there; spill
                    # everything after the consumed prefix
                    consumed = idx
                    acc = 0
                    for r in replies[idx:chunk_end]:
                        acc += (4 + len(r) + 7) & ~7
                        if acc > off:
                            break
                        consumed += 1
                    return self._fast_spill_replies(ring, replies[consumed:])
            idx = chunk_end
        return 0

    def _fast_spill_replies(self, ring, recs) -> int:
        """Result-ring-full spill: ship undelivered completion records to
        the driver over the RPC path (the slow road stays the backstop in
        BOTH directions). Falls back to a blocking ring push when no
        spill address is known or the driver is unreachable — in the
        latter case the driver is gone and its break-lane recovery (or
        teardown) owns the records."""
        from ray_tpu.core import fastpath

        owner = getattr(ring, "_owner_addr", None)
        if owner is not None:
            try:
                fut = asyncio.run_coroutine_threadsafe(
                    self._send_spilled_results(owner, list(recs)),
                    self.core.loop)
                fut.result(30)  # raylint: disable=RT020 -- ring-full spill backstop: the pump MUST backpressure here
                return 0
            except Exception:
                # ambiguous failure (e.g. timeout with the RPC still in
                # flight): the ring re-push below may duplicate records —
                # safe, the driver applies completions exactly once
                log.debug("result spill over RPC failed", exc_info=True)
        # blocking fallback, chunked so one frame can never exceed the
        # ring capacity (kTooBig would tear down the whole lane)
        chunk: list = []
        chunk_bytes = 0
        for rec in recs:
            if chunk and chunk_bytes + len(rec) > 512 * 1024:
                status = ring.push_raw(fastpath.REP, fastpath.frame(chunk))
                if status != 0:
                    return status
                chunk, chunk_bytes = [], 0
            chunk.append(rec)
            chunk_bytes += len(rec)
        if chunk:
            return ring.push_raw(fastpath.REP, fastpath.frame(chunk))
        return 0

    async def _send_spilled_results(self, owner: tuple, recs: list):
        conn = self._spill_conns.get(owner)
        if conn is None or conn._closed:
            conn = await rpc.connect(*owner, timeout=10)
            self._spill_conns[owner] = conn
        await conn.call("fast_result", {"records": recs}, timeout=20)

    # hot-mode tuning: 5ms pop slices, ~20 empty slices (~100ms) to park
    _PUMP_HOT_POP_MS = 5
    _PUMP_IDLE_CYCLES = 20

    def _fast_actor_park(self, ring, state: dict):
        """Parked-mode keeper thread: blocks on the ring with LONG
        timeouts (costless while idle), and on traffic executes the first
        batch via the executor (one handoff) then hands consumption to
        the executor-resident hot cycle until it idles out again."""
        from ray_tpu.core import fastpath

        try:
            while not self._exit_requested:
                recs = ring.pop_batch(fastpath.SUB, timeout_ms=1000)
                if recs is None:
                    self._fast_pump_close(ring)
                    return
                if not recs:
                    continue
                if state.get("dispatch_only"):
                    # async/threaded/grouped actor: this thread pops and
                    # dispatches in ring order, never executes user code
                    # (replies stream back as each dispatched call ends)
                    if not self._fast_actor_exec_batch(ring, state, recs):
                        self._fast_pump_close(ring)
                        return
                    continue
                state["idle"] = 0
                state["parked"].clear()
                try:
                    self.executor.submit(
                        self._fast_actor_pump_batch, ring, state, recs)
                except RuntimeError:  # executor shut down
                    self._fast_pump_close(ring)
                    return
                # the hot chain owns the ring until it parks again
                while not (state["parked"].wait(1.0)
                           or self._exit_requested):
                    pass
                if state.get("closed"):
                    return
        except BaseException:
            self._fast_pump_close(ring)
            raise

    def _fast_actor_pump_batch(self, ring, state: dict, recs):
        """First batch of a busy period (on the executor thread), then
        chain into the hot cycle. Any escape hatch closes the ring and
        wakes the keeper — an exception parked in the unchecked executor
        Future would otherwise leave the keeper waiting forever while the
        driver blocks on replies that never come."""
        try:
            if self._fast_actor_exec_batch(ring, state, recs):
                self._fast_actor_pump_cycle(ring, state)
                return
        except BaseException:  # noqa: BLE001 — never leave the ring open
            self._fast_pump_close(ring)
            state["closed"] = True
            state["parked"].set()
            raise
        self._fast_pump_close(ring)  # reply push failed: ring is done
        state["closed"] = True
        state["parked"].set()

    @staticmethod
    def _classify_method(m) -> str:
        """One fast-lane verdict for a callable: sync | async | gen."""
        if inspect.isgeneratorfunction(m) or inspect.isasyncgenfunction(m):
            return "gen"
        if inspect.iscoroutinefunction(m):
            return "async"
        return "sync"

    def _actor_fast_verdict(self, mname: str):
        """(verdict, group) for one method — init-time table hit in the
        steady state (satellite: no per-record getattr + inspect.is*);
        dynamically-added callables classify once on first sight and are
        cached. None = not callable here (NEED_SLOW: the RPC path owns
        the error surface)."""
        table = getattr(self, "_actor_method_table", None)
        if table is None:
            table = self._actor_method_table = {}
        v = table.get(mname)
        if v is not None:
            return v
        inst = self.actor_instance
        m = getattr(inst, mname, None) if inst is not None else None
        if not callable(m):
            return None
        v = table[mname] = (self._classify_method(m),
                            self._method_groups.get(mname))
        return v

    def _build_actor_method_table(self, cls) -> dict:
        """Precompute every public method's fast-lane verdict ONCE at
        actor init: name -> (sync|async|gen, concurrency_group). Walks
        the CLASS (dir covers the MRO) so property getters never fire;
        instance-assigned callables classify lazily via
        _actor_fast_verdict. Shipped to the driver in the
        attach_fast_ring reply (protocol 1.8) so ineligible methods are
        routed to the RPC path per call without a ring round trip."""
        table: dict = {}
        for name in dir(cls):
            if name.startswith("_"):
                continue
            m = getattr(cls, name, None)
            if not callable(m):
                continue
            table[name] = (self._classify_method(m),
                           self._method_groups.get(name))
        return table

    def _fast_actor_exec_batch(self, ring, state: dict, recs) -> bool:
        """One batch of actor ring records, in ring (= per-caller FIFO)
        order; False = ring done. Sync methods on an inline lane execute
        right here (zero handoffs); async / grouped / threaded-actor
        methods are handed to the event loop IN ORDER and reply as each
        finishes — dispatch stays the FIFO invariant, completion does
        not (the reply's seq lets the driver match them out of order)."""
        from ray_tpu.core import fastpath
        from ray_tpu.utils import recorder as _rec

        inline_max = self.cfg.fastpath_inline_result_max
        inst = self.actor_instance
        rec_r = _rec.get_recorder()
        loop = self.core.loop
        t_prev = t_pop = time.perf_counter_ns()
        if rec_r is not None:
            rec_r.record(b"", _rec.WORKER_POP, t_pop, a0=len(recs))
        replies = []
        dispatch_items = []
        for rec in recs:
            tid, mkey, args, kwargs, t_sub, seq, trc = \
                fastpath.unpack_actor_task(rec)
            stream = mkey[:3] == b"gm:"  # stream-called generator (2.3)
            mname = mkey[3:].decode()  # b"am:<method>" / b"gm:<method>"
            verdict = None if state["downgraded"] or inst is None \
                else self._actor_fast_verdict(mname)
            if verdict is None or (verdict[0] == "gen") is not stream:
                # Sticky for the in-flight tail: replies stream back in
                # ring order from here, the driver requeues them over RPC
                # in FIFO order and retires the lane. Reaching this means
                # the driver's copy of the eligibility table missed the
                # method (added after attach) — the ordinary tables keep
                # generators off the ring entirely (and stream submits
                # ON it: a "gm:" record whose method is no longer a
                # generator downgrades the same way).
                state["downgraded"] = True
                replies.append(fastpath.pack_reply(
                    tid, fastpath.NEED_SLOW, b"", seq=seq))
                t_prev = time.perf_counter_ns()  # skipped record: don't
                # bill its handling to the next record's deserialize
                continue
            if stream:
                # generator drive always lives on the loop: chunks flush
                # through _fast_reply_one as the method yields, beside
                # any async batch-mates (per-stream chunk seq keeps the
                # driver's ordering; lane FIFO only covers dispatch)
                dispatch_items.append((tid, mname, "gen", verdict[1],
                                       args, kwargs, t_sub, t_pop, seq,
                                       trc))
                t_prev = time.perf_counter_ns()
                continue
            kind, group = verdict
            if (kind == "async" or group
                    or state.get("dispatch_only")):
                # out-of-order completion lane: collected in ring order,
                # handed to the loop in ONE wake per batch below; each
                # coroutine replies when its call ends
                dispatch_items.append((tid, mname, kind, group, args,
                                       kwargs, t_sub, t_pop, seq, trc))
                t_prev = time.perf_counter_ns()
                continue
            t_x0 = time.perf_counter_ns()
            try:
                if chaos.ENABLED:
                    chaos.point("worker.exec", name=mname, fast=1)
                m = getattr(inst, mname)
                if self._trace_on:  # sampled: exec span; else suppress
                    with self._fast_exec_span(trc, tid, mname, "ring"):
                        ok, val = True, m(*args, **kwargs)
                else:
                    ok, val = True, m(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — reply on
                ok, val = False, e
            t_x1 = time.perf_counter_ns()
            ring_ns = t_pop - t_sub if t_sub else 0
            deser_ns = t_x0 - t_prev
            exec_ns = t_x1 - t_x0
            t_prev = t_x1
            replies.append(self._fast_pack_result(
                tid, ok, val, inline_max,
                fastpath.pack_stamp(ring_ns, deser_ns, exec_ns)
                if t_sub else b"", seq=seq, trace=trc))
            if rec_r is not None:
                # same 1-in-16 W_TASK sampling as the normal pump (the
                # counter lives on self: batches don't reset it)
                self._rec_wt_n += 1
                if not (self._rec_wt_n & 15):
                    rec_r.record_wtask(
                        tid, t_x1, min(max(ring_ns, 0), 0xFFFFFFFF),
                        min(deser_ns, 0xFFFFFFFF), exec_ns)
        if dispatch_items:
            # ONE self-pipe wake for the whole batch (a wake per record
            # measured as the difference between parity and a 2x win on
            # pipelined async bursts); create_task order inside the
            # callback preserves ring order = dispatch FIFO
            try:
                loop.call_soon_threadsafe(
                    self._fast_dispatch_records, ring, dispatch_items)
            except RuntimeError:
                return False  # loop gone (worker exit): ring is done
        if not replies:
            return True  # pure-dispatch batch: nothing to push from here
        ok_push = self._fast_push_replies(ring, replies) == 0
        if rec_r is not None:
            rec_r.record(b"", _rec.COMPLETION_PUSH, a0=len(replies))
        return ok_push

    def _fast_dispatch_records(self, ring, items):
        """Loop-side fan-out of one dispatched batch, in ring order. The
        tasks are strongly held until done — the loop only keeps weak
        refs, and a GC'd pending task would eat its reply and wedge the
        driver's inflight accounting."""
        loop = asyncio.get_running_loop()
        pending = getattr(self, "_fast_dispatch_pending", None)
        if pending is None:
            pending = self._fast_dispatch_pending = set()
        for it in items:
            t = loop.create_task(self._fast_exec_dispatched(ring, *it))
            pending.add(t)
            t.add_done_callback(pending.discard)

    async def _fast_exec_dispatched(self, ring, tid, mname, kind, group,
                                    args, kwargs, t_sub, t_pop, seq,
                                    trc=b"", transport="ring"):
        """Loop-side execution of one dispatched actor ring record: async
        methods run on the loop (group semaphore honored), sync methods
        of threaded/grouped actors on the right pool — exactly where the
        RPC path runs them — then the reply pushes as THIS call
        finishes, out of order with its batch-mates."""
        from ray_tpu.core import fastpath

        if kind == "gen":  # stream-called generator ("gm:" record, 2.3)
            await self._fast_exec_stream(ring, tid, mname, group, args,
                                         kwargs, t_sub, t_pop, seq, trc,
                                         transport)
            return
        inst = self.actor_instance
        span = (self._fast_exec_span(trc, tid, mname, transport)
                if self._trace_on else None)
        t_x0 = time.perf_counter_ns()
        try:
            if chaos.ENABLED:
                chaos.point("worker.exec", name=mname, fast=1)
            m = getattr(inst, mname)
            if group and group not in self._group_execs:
                # loud, exactly like the RPC path (rpc_push_actor_task):
                # silently running on the default pool would lose the
                # isolation the group asked for
                raise TaskError(
                    f"concurrency group {group!r} not declared on this "
                    f"actor (declared: {sorted(self._group_execs)})")
            if span is not None:
                span.__enter__()  # CM protocol inline: the exit must
                # run before the reply packs, exceptions included
            if kind == "async":
                sem = self._group_sems.get(group) if group else None
                if sem is not None:
                    async with sem:  # group-bounded async slots
                        val = await m(*args, **kwargs)
                else:
                    val = await m(*args, **kwargs)
            else:
                executor = (self._group_execs[group] if group
                            else self.executor)
                if span is not None:
                    # run_in_executor does NOT copy contextvars (unlike
                    # asyncio.to_thread): carry the span context — or
                    # the UNSAMPLED suppression — into the pool thread
                    # so nested .remote() calls from a threaded/grouped
                    # sync method chain (or stay suppressed) correctly
                    import contextvars as _cv

                    cctx = _cv.copy_context()
                    val = await asyncio.get_running_loop().run_in_executor(
                        executor, lambda: cctx.run(m, *args, **kwargs))
                else:
                    val = await asyncio.get_running_loop().run_in_executor(
                        executor, lambda: m(*args, **kwargs))
            ok = True
            if span is not None:
                span.__exit__(None, None, None)
        except BaseException as e:  # noqa: BLE001 — reply on
            ok, val = False, e
            if span is not None and span._token is not None:
                span.__exit__(type(e), e, None)
        t_x1 = time.perf_counter_ns()
        if t_sub:
            # the dispatch hop (pump -> loop/pool) rides the deserialize
            # stage; exec covers the await, so concurrent async calls
            # overlap inside it — per-call wall, not CPU
            stamp = fastpath.pack_stamp(
                t_pop - t_sub, max(0, t_x0 - t_pop), t_x1 - t_x0)
        else:
            stamp = b""
        rep = self._fast_pack_result(
            tid, ok, val, self.cfg.fastpath_inline_result_max, stamp,
            seq=seq, node=getattr(ring, "_desc_node", None), trace=trc)
        await self._fast_reply_one(ring, rep)

    async def _fast_reply_one(self, ring, rec: bytes) -> bool:
        """Completion push for one out-of-order reply, loop-side (the
        ring mutex makes the pump thread + loop concurrent producers
        safe). Mirrors _fast_push_replies' semantics without blocking
        the loop: non-blocking pushes with short async backoffs, then
        the RPC spill once the result ring has stayed full past the
        spill deadline. Returns False when the ring is CLOSED (the
        driver broke the lane — its recovery owns whatever did not
        land); stream pumps use that to stop flushing chunks to a
        consumer that is gone."""
        from ray_tpu.core import fastpath

        framed = fastpath.frame_one(rec)
        loop = asyncio.get_running_loop()
        deadline = (loop.time()
                    + max(1, self.cfg.fastpath_reply_spill_ms) / 1000.0)
        while True:
            took = ring.push_batch(fastpath.REP, framed, 0)
            if took < 0:
                return False  # ring closed (driver recovery owns it)
            if took >= len(framed):
                return True  # delivered
            if loop.time() >= deadline:
                owner = getattr(ring, "_owner_addr", None)
                if owner is not None:
                    try:
                        await self._send_spilled_results(owner, [rec])
                        return True
                    except Exception:
                        # driver unreachable over RPC too: keep nudging
                        # the ring until it closes (break-lane recovery)
                        log.debug("ooo result spill failed", exc_info=True)
                deadline = loop.time() + 0.1
            await asyncio.sleep(0.002)

    async def _fast_reply_burst(self, ring, recs) -> bool:
        """Push a burst of stream chunk records in ONE ring lock round
        and at most one consumer wake (rt_ring_push_batch takes whole
        records) — on a small host the per-push wake syscalls alone cost
        a context switch each. Whatever does not fit immediately falls
        back to the per-record spill-backed push."""
        from ray_tpu.core import fastpath

        if len(recs) == 1:
            return await self._fast_reply_one(ring, recs[0])
        framed = [fastpath.frame_one(r) for r in recs]
        buf = b"".join(framed)
        took = ring.push_batch(fastpath.REP, buf, 0)
        if took < 0:
            return False  # ring closed (driver recovery owns it)
        if took >= len(buf):
            return True
        i, off = 0, 0  # took lands on a whole-record boundary
        while off < took:
            off += len(framed[i])
            i += 1
        for rec in recs[i:]:
            if not await self._fast_reply_one(ring, rec):
                return False
        return True

    async def _fast_exec_stream(self, ring, tid, mname, group, args,
                                kwargs, t_sub, t_pop, seq, trc=b"",
                                transport="ring"):
        """Drive one stream-called generator method ("gm:" record, wire
        2.3): one "G" chunk record per yielded item through
        :meth:`_fast_reply_one` (ring or tunnel sink — the same
        spill-backed push out-of-order replies use), then ONE ordinary
        terminal reply (OK + chunk count, or ERR) on the lane's seq
        machinery. Async generators run on the loop; sync generators
        pull each item on the actor's executor/group pool (where the
        RPC path would run them). The drive stops early when the
        driver abandons the stream (rpc_stream_abandon — client
        disconnect) or the ring closes under us; either way the user
        generator is closed so GeneratorExit reaches its finally (the
        cancellation surface: an LLM stream's finally frees its decode
        slot)."""
        from ray_tpu.core import fastpath

        inst = self.actor_instance
        inline_max = self.cfg.fastpath_inline_result_max
        node = getattr(ring, "_desc_node", None)
        aborts = getattr(self, "_fast_stream_aborts", None)
        if aborts is None:
            aborts = self._fast_stream_aborts = set()
        span = (self._fast_exec_span(trc, tid, mname, transport)
                if self._trace_on else None)
        loop = asyncio.get_running_loop()
        t_x0 = time.perf_counter_ns()
        nchunks = 0
        agen = it = None
        pending = None  # in-flight agen.__anext__ carried between bursts
        ok, err = True, None
        try:
            if chaos.ENABLED:
                chaos.point("worker.exec", name=mname, fast=1, stream=1)
            m = getattr(inst, mname)
            if group and group not in self._group_execs:
                raise TaskError(
                    f"concurrency group {group!r} not declared on this "
                    f"actor (declared: {sorted(self._group_execs)})")
            if span is not None:
                span.__enter__()
            executor = (self._group_execs[group] if group
                        else self.executor)
            if inspect.isasyncgenfunction(m):
                agen = m(*args, **kwargs)
            else:
                gen = await loop.run_in_executor(
                    executor, lambda: m(*args, **kwargs))
                if hasattr(gen, "__anext__"):
                    agen = gen  # method returned an async generator
                else:
                    it = iter(gen)
            _end = object()

            def _pull_batch(nmax=64, budget_s=5e-4):
                # amortize the executor round-trip (~hundreds of µs of
                # thread wakeups) over every item a fast sync generator
                # has ready: keep pulling until the time budget or nmax.
                # A slow generator exits after ONE item (its next() alone
                # blows the budget), so per-chunk latency is unchanged
                # where it matters and throughput-bound streams stop
                # paying a threadpool hop per chunk. A mid-batch user
                # exception is DEFERRED, never raised here: the already-
                # pulled prefix must flush as chunks before the error
                # becomes the stream's terminal.
                out = []
                err = None
                t0 = time.perf_counter()
                try:
                    while len(out) < nmax:
                        out.append(next(it))
                        if time.perf_counter() - t0 >= budget_s:
                            break
                except StopIteration:
                    out.append(_end)
                except BaseException as e:  # noqa: BLE001 — deferred
                    err = e
                return out, err

            async def _drive(coro, f):
                # finish a partially-stepped __anext__ coroutine in THIS
                # task — context continuity: the generator body may hold
                # contextvar tokens (serve's deadline), so every step
                # must run under one Context, which rules out wrapping
                # the coroutine in a fresh Task
                while True:
                    if f is not None and hasattr(
                            f, "_asyncio_future_blocking"):
                        f._asyncio_future_blocking = False
                        try:
                            await f
                        except BaseException:  # raylint: disable=RT012 — not a swallow: the frame re-raises from f.result() at the next send
                            pass
                    else:
                        await asyncio.sleep(0)
                    try:
                        f = coro.send(None)
                    except StopIteration as si:
                        return si.value

            done = False
            defer_err = None  # user error held until its prefix flushes
            while not done:
                if tid in aborts:
                    break  # consumer is gone: close the generator below
                if agen is not None:
                    items = []
                    if pending is not None:
                        coro, f = pending
                        pending = None
                        try:
                            items.append(await _drive(coro, f))
                        except StopAsyncIteration:
                            done = True
                        except BaseException as e:  # noqa: BLE001
                            defer_err = e
                            done = True
                    # greedy ready-drain: step __anext__ synchronously —
                    # a producer with items buffered (the serve replica
                    # wrapper's pool batch, a decode block) yields each
                    # without suspending, so the whole backlog lands in
                    # ONE burst (one ring push + one consumer wake)
                    # instead of a push per item
                    while not done and len(items) < 64:
                        coro = agen.__anext__()
                        try:
                            f = coro.send(None)
                        except StopIteration as si:
                            items.append(si.value)
                            continue
                        except StopAsyncIteration:
                            done = True
                            break
                        except BaseException as e:  # noqa: BLE001
                            defer_err = e
                            done = True
                            break
                        # producer suspended: flush what is ready now;
                        # the parked step resumes after the burst lands
                        if items:
                            pending = (coro, f)
                        else:
                            try:
                                items.append(await _drive(coro, f))
                            except StopAsyncIteration:
                                done = True
                            except BaseException as e:  # noqa: BLE001
                                defer_err = e
                                done = True
                        break
                else:
                    items, defer_err = await loop.run_in_executor(
                        executor, _pull_batch)
                    if defer_err is not None:
                        done = True
                burst = []
                for item in items:
                    if item is _end:
                        done = True
                        break
                    burst.append(self._fast_pack_chunk(
                        tid, item, inline_max, nchunks, node, trc))
                    nchunks += 1
                if burst and not await self._fast_reply_burst(ring, burst):
                    return  # ring closed: recovery owns it
            if defer_err is not None:
                raise defer_err
            if span is not None:
                span.__exit__(None, None, None)
        except BaseException as e:  # noqa: BLE001 — reply on
            ok, err = False, e
            if span is not None and span._token is not None:
                span.__exit__(type(e), e, None)
        finally:
            aborts.discard(tid)
            if agen is not None:
                if pending is not None:
                    # a parked __anext__ is mid-flight inside the
                    # generator: close the step (GeneratorExit reaches
                    # the body's finally) or aclose would see it
                    # "already running"
                    try:
                        pending[0].close()
                    except BaseException:  # raylint: disable=RT012 — cleanup: aclose below reports the real failure
                        pass
                try:
                    await agen.aclose()
                except BaseException:  # noqa: BLE001 — cleanup only
                    log.debug("stream aclose failed", exc_info=True)
            elif it is not None:
                try:
                    await loop.run_in_executor(None, it.close)
                except BaseException:  # noqa: BLE001 — cleanup only
                    log.debug("stream close failed", exc_info=True)
        t_x1 = time.perf_counter_ns()
        stamp = (fastpath.pack_stamp(t_pop - t_sub, max(0, t_x0 - t_pop),
                                     t_x1 - t_x0) if t_sub else b"")
        if ok:
            rep = fastpath.pack_reply(tid, fastpath.OK,
                                      fastpath.pack_stream_fin(nchunks),
                                      stamp, seq, trc)
        else:
            rep = fastpath.pack_reply(tid, fastpath.ERR,
                                      self._fast_pack_error(err), stamp,
                                      seq, trc)
        await self._fast_reply_one(ring, rep)

    def _fast_pack_chunk(self, tid: bytes, item, inline_max: int,
                         chunk_seq: int, node: bytes | None,
                         trc: bytes = b"") -> bytes:
        """Pack one yielded item as a "G" chunk record: inline when it
        fits, else sealed into the node arena under return index
        chunk_seq + 1 (index 0 stays the terminal reply's) and shipped
        as a shm size/desc — exactly the OK_SHM economics, per chunk.
        An unpackable item raises, which ends the stream with a terminal
        ERR — loud at the consumer, never a silent skip."""
        from ray_tpu.core import fastpath

        t_ns = time.perf_counter_ns()
        try:
            meta, buffers = serialization.dumps_with_buffers(item)
            size = serialization.total_size(meta, buffers)
            payload = _pack_bytes(meta, buffers, size)
            if size <= inline_max:
                return fastpath.pack_chunk(tid, fastpath.CHUNK, payload,
                                           chunk_seq, t_ns, trc)
            oid = ObjectID.for_task_return(TaskID(tid), chunk_seq + 1)
            if not self.core.store.contains(oid):
                self.core.store.put_raw(oid, payload)
            return fastpath.pack_chunk(
                tid, fastpath.CHUNK_SHM,
                fastpath.pack_shm_desc(size, node) if node is not None
                else fastpath.pack_shm_size(size),
                chunk_seq, t_ns, trc)
        except Exception as e:
            raise TaskError(f"unpackable stream item: {e!r}") from e

    async def rpc_stream_abandon(self, conn, p):
        """Driver-side consumer of an open stream went away (client
        disconnect, sink aclose): stop flushing its chunks and close
        the user generator at the next yield point. Best-effort notify
        — an id that never arrives just means the stream runs to its
        natural end against a closed ring."""
        aborts = getattr(self, "_fast_stream_aborts", None)
        if aborts is None:
            aborts = self._fast_stream_aborts = set()
        for tid in p.get("task_ids", ()):
            aborts.add(bytes(tid))
        return True

    # -------------------------------------------- node tunnel (core/tunnel.py)
    async def rpc_tunnel_attach(self, conn, p):
        """The local raylet binds one tunnel lane onto this worker on
        behalf of a remote driver (protocol 2.0). Records arrive as
        ``tunnel_records`` frames — the SAME packed records the shm
        rings carry — and replies coalesce back per loop tick through a
        :class:`_TunnelSink`. Actor lanes ship the method eligibility
        table exactly like ``attach_fast_ring`` does."""
        lane = int(p["lane"])
        st = {"lane": lane, "kind": p.get("kind", "task"), "conn": conn,
              "downgraded": False, "reply_buf": [], "reply_armed": False,
              "closed": False}
        st["sink"] = _TunnelSink(self, st)
        if st["kind"] == "actor":
            # same verdict as attach_fast_ring: a pure-sync serial actor
            # executes whole record batches INLINE on its executor thread
            # (one handoff per batch, not two per call); async/threaded/
            # grouped actors dispatch per record and reply out of order
            table = getattr(self, "_actor_method_table", None)
            st["dispatch_only"] = (
                getattr(self, "_actor_max_concurrency", 1) > 1
                or bool(self._group_execs)
                or any(v[0] == "async" for v in (table or {}).values()))
            self._tunnel_lanes[lane] = st
            return {"ok": True, "methods": table}
        self._tunnel_lanes[lane] = st
        return {"ok": True}

    async def rpc_tunnel_detach(self, conn, p):
        for lane in p.get("lanes", ()):
            st = self._tunnel_lanes.pop(lane, None)
            if st is not None:
                st["closed"] = True
        return True

    async def rpc_tunnel_records(self, conn, p):
        """One tunnel frame's records for this worker (notify). Records
        are dispatched in frame order — dispatch order IS the caller's
        FIFO invariant, completion order is not (each call replies as it
        finishes, seq-matched driver-side like ring completions).

        Batch execution mirrors the ring pump's economics: a pure-sync
        serial actor's batch (and any task-record batch) runs in ONE
        executor hop and replies as one coalesced frame — per-record
        thread handoffs were most of the tunnel's worker-side cost.
        Records that need the loop (async/grouped methods, descriptor
        args) dispatch per record instead."""
        from ray_tpu.core import fastpath

        loop = asyncio.get_running_loop()
        t_pop = time.perf_counter_ns()
        for lane, recs_b in p["frames"]:
            st = self._tunnel_lanes.get(lane)
            if st is None:
                continue
            st["conn"] = conn  # reply on the conn the records rode in on
            recs = fastpath.unframe(recs_b)
            if st["kind"] == "task":
                try:
                    self.executor.submit(self._tunnel_exec_task_batch,
                                         st, recs, t_pop)
                except RuntimeError:
                    return  # executor shut down (worker exit)
                continue
            if not st.get("dispatch_only") and not st["downgraded"]:
                chain = st.get("seq_chain")
                if chain is not None and chain.done():
                    chain = st["seq_chain"] = None
                if chain is None \
                        and not any(self._rec_has_desc(r) for r in recs):
                    try:
                        self.executor.submit(self._tunnel_exec_batch_sync,
                                             st, recs, t_pop)
                    except RuntimeError:
                        return
                else:
                    # descriptor args force the loop's batched pull; a
                    # serial actor's records still run strictly in
                    # order — and so must every LATER frame while the
                    # chain drains (a plain batch hopping straight to
                    # the executor would overtake a record awaiting its
                    # pull), so frames append to the chain until it
                    # empties
                    t = loop.create_task(
                        self._tunnel_exec_seq(st, chain, recs, t_pop))
                    st["seq_chain"] = t
                    self._tunnel_tasks.add(t)
                    t.add_done_callback(self._tunnel_tasks.discard)
                continue
            for rec in recs:
                t = loop.create_task(self._tunnel_exec_one(st, rec, t_pop))
                self._tunnel_tasks.add(t)
                t.add_done_callback(self._tunnel_tasks.discard)

    async def _tunnel_exec_seq(self, st, prev, recs, t_pop: int):
        """Sequential batch leg for a SERIAL actor's records when some
        carry descriptors: each record completes before the next
        dispatches (and after the previous chained frame), preserving
        the per-caller FIFO the serial executor would otherwise
        provide."""
        if prev is not None:
            try:
                await asyncio.shield(prev)
            except Exception:
                # the prior frame already replied its own errors; this
                # await exists only for ordering
                log.debug("chained tunnel frame failed", exc_info=True)
        for rec in recs:
            await self._tunnel_exec_one(st, rec, t_pop)

    @staticmethod
    def _tunnel_t_sub(t_sub: int, t_pop: int) -> int:
        """Cross-host stamp guard: tunnel records may carry a submit
        stamp from a DIFFERENT host's CLOCK_MONOTONIC base. When the
        delta is implausible (>5 min) the stamp drops so stage samples
        degrade to exec-only truth instead of clamped garbage;
        same-host tunnels (one-host multi-raylet, in-process clusters)
        keep exact stamps."""
        return (t_sub if t_sub and abs(t_pop - t_sub) < 300_000_000_000
                else 0)

    @staticmethod
    def _rec_has_desc(rec: bytes) -> bool:
        """Cheap pre-check: only serialization.pack records ("C") can
        carry TunnelArgRef descriptors — C-pickled "A" bodies are simple
        immutables by construction."""
        return rec[:1] == b"C" and b"TunnelArgRef" in rec

    def _tunnel_exec_batch_sync(self, st, recs, t_pop: int):
        """One tunnel batch of a pure-sync serial actor, ON the actor's
        executor thread (the ring pump's inline shape: zero per-call
        handoffs, state affinity identical to the RPC path). Replies
        push as ONE coalesced frame."""
        from ray_tpu.core import fastpath

        inline_max = self.cfg.fastpath_inline_result_max
        inst = self.actor_instance
        node = self.node_id.binary()
        replies = []
        t_prev = time.perf_counter_ns()
        for rec in recs:
            tid, mkey, args, kwargs, t_sub, seq, trc = \
                fastpath.unpack_actor_task(rec)
            t_sub = self._tunnel_t_sub(t_sub, t_pop)
            mname = mkey[3:].decode()
            verdict = None if st["downgraded"] or inst is None \
                else self._actor_fast_verdict(mname)
            if (mkey[:3] == b"gm:" and verdict is not None
                    and verdict[0] == "gen"):
                # stream call mixed into a sync serial batch: the
                # generator drive lives on the loop (chunks flush as it
                # yields) — stream calls are unordered by contract, so
                # hopping out of the serial batch is safe
                try:
                    asyncio.run_coroutine_threadsafe(
                        self._tunnel_exec_record_on_loop(st, rec, t_pop),
                        self.core.loop)
                except RuntimeError:
                    return  # loop gone (worker exit)
                t_prev = time.perf_counter_ns()
                continue
            if verdict is None or verdict[0] != "sync" or verdict[1]:
                st["downgraded"] = True
                replies.append(fastpath.pack_reply(
                    tid, fastpath.NEED_SLOW, b"", seq=seq))
                t_prev = time.perf_counter_ns()
                continue
            t_x0 = time.perf_counter_ns()
            try:
                if chaos.ENABLED:
                    chaos.point("worker.exec", name=mname, fast=1)
                m = getattr(inst, mname)
                if self._trace_on:  # sampled: exec span; else suppress
                    with self._fast_exec_span(trc, tid, mname, "tunnel"):
                        ok, val = True, m(*args, **(kwargs or {}))
                else:
                    ok, val = True, m(*args, **(kwargs or {}))
            except BaseException as e:  # noqa: BLE001 — reply on
                ok, val = False, e
            t_x1 = time.perf_counter_ns()
            stamp = (fastpath.pack_stamp(max(0, t_pop - t_sub),
                                         max(0, t_x0 - t_prev),
                                         t_x1 - t_x0)
                     if t_sub else b"")
            t_prev = t_x1
            replies.append(self._fast_pack_result(
                tid, ok, val, inline_max, stamp, seq=seq, node=node,
                trace=trc))
        if replies:
            st["sink"].push_batch(fastpath.REP, fastpath.frame(replies))

    def _tunnel_exec_task_batch(self, st, recs, t_pop: int):
        """One tunnel batch of plain task records, ON the task executor
        thread (records with descriptor args bounce to the loop path for
        their async batched pull). Functions resolve through a local
        cache; a miss bridges to the loop like the ring pump's loader."""
        from ray_tpu.core import fastpath

        inline_max = self.cfg.fastpath_inline_result_max
        node = self.node_id.binary()
        cache = getattr(self, "_tunnel_funcs", None)
        if cache is None:
            cache = self._tunnel_funcs = {}
        loop = self.core.loop
        replies = []
        t_prev = t_pop  # rolling: each record's deser starts where the
        #                 previous one ended, not at the frame pop (the
        #                 ring pump's accounting — billing the whole
        #                 batch's earlier exec to later records' deser
        #                 would inflate deser p99 ~N-fold under burst)
        for rec in recs:
            if self._rec_has_desc(rec):
                # descriptor args need the loop's batched pull
                try:
                    asyncio.run_coroutine_threadsafe(
                        self._tunnel_exec_record_on_loop(st, rec, t_pop),
                        loop)
                except RuntimeError:
                    return
                t_prev = time.perf_counter_ns()
                continue
            tid, func_id, args, kwargs, t_sub, trc = \
                fastpath.unpack_task(rec)
            t_sub = self._tunnel_t_sub(t_sub, t_pop)
            fn = cache.get(func_id)
            if fn is None:
                try:
                    fut = asyncio.run_coroutine_threadsafe(
                        self._load_function(func_id), loop)
                    # function-cache miss: first call per func_id
                    # only, amortized to zero
                    fn = fut.result(15)  # raylint: disable=RT020 -- cache miss
                    cache[func_id] = fn  # only successes cache: a
                    # transient load failure must not downgrade the
                    # function to the RPC path for this worker's lifetime
                except Exception:
                    fn = None
            if (fn is None or inspect.iscoroutinefunction(fn)
                    or inspect.isgeneratorfunction(fn)
                    or inspect.isasyncgenfunction(fn)):
                replies.append(fastpath.pack_reply(
                    tid, fastpath.NEED_SLOW, b""))
                t_prev = time.perf_counter_ns()
                continue
            t_x0 = time.perf_counter_ns()
            try:
                with self._exec_mutex:
                    if chaos.ENABLED:
                        chaos.point("worker.exec",
                                    name=getattr(fn, "__name__", "task"),
                                    fast=1)
                    if self._trace_on:  # sampled: span; else suppress
                        with self._fast_exec_span(
                                trc, tid, getattr(fn, "__name__", "task"),
                                "tunnel"):
                            ok, val = True, fn(*args, **(kwargs or {}))
                    else:
                        ok, val = True, fn(*args, **(kwargs or {}))
            except BaseException as e:  # noqa: BLE001 — reply on
                ok, val = False, e
            t_x1 = time.perf_counter_ns()
            stamp = (fastpath.pack_stamp(max(0, t_pop - t_sub),
                                         max(0, t_x0 - t_prev),
                                         t_x1 - t_x0)
                     if t_sub else b"")
            t_prev = t_x1
            replies.append(self._fast_pack_result(
                tid, ok, val, inline_max, stamp, node=node, trace=trc))
        if replies:
            st["sink"].push_batch(fastpath.REP, fastpath.frame(replies))

    async def _tunnel_exec_record_on_loop(self, st, rec: bytes,
                                          t_pop: int):
        """Loop-side hand-off for a task record the executor batch could
        not run inline (descriptor args)."""
        t = asyncio.get_running_loop().create_task(
            self._tunnel_exec_one(st, rec, t_pop))
        self._tunnel_tasks.add(t)
        t.add_done_callback(self._tunnel_tasks.discard)

    async def _resolve_tunnel_descs(self, args, kwargs):
        """Adopt TunnelArgRef descriptors (oversized args the sender
        sealed into ITS shm arena): ONE batched pull_objects round trip
        through the local raylet for the whole set, then the values read
        out of local shm. The sender pins the sealed copies until this
        call's reply lands, so the pull can't race the free."""
        from ray_tpu.core import fastpath

        descs = [a for a in args if isinstance(a, fastpath.TunnelArgRef)]
        if kwargs:
            descs += [v for v in kwargs.values()
                      if isinstance(v, fastpath.TunnelArgRef)]
        if not descs:
            return args, kwargs
        hints = {}
        for d in descs:
            hints.setdefault(ObjectID(d.oid), set()).add(d.node)
        await self.core.pull_objects_batch(hints)
        refs = {d.oid: ObjectRef(ObjectID(d.oid), d.owner) for d in descs}
        order = list(refs)
        vals = await self.core.get_async([refs[o] for o in order], None)
        got = dict(zip(order, vals))
        args = tuple(got[a.oid] if isinstance(a, fastpath.TunnelArgRef)
                     else a for a in args)
        if kwargs:
            kwargs = {k: got[v.oid]
                      if isinstance(v, fastpath.TunnelArgRef) else v
                      for k, v in kwargs.items()}
        return args, kwargs

    async def _tunnel_exec_one(self, st, rec: bytes, t_pop: int):
        """Execute one tunnel record and push its reply through the
        lane's sink. Actor records ride the exact dispatch path ring
        records do (_fast_exec_dispatched: async methods on the loop,
        sync methods on the actor's executor/group pool); task records
        execute on the task executor under the one-task mutex."""
        from ray_tpu.core import fastpath

        sink = st["sink"]
        if st["kind"] == "actor":
            tid, mkey, args, kwargs, t_sub, seq, trc = \
                fastpath.unpack_actor_task(rec)
            t_sub = self._tunnel_t_sub(t_sub, t_pop)
            stream = mkey[:3] == b"gm:"  # stream-called generator (2.3)
            mname = mkey[3:].decode()
            verdict = None
            if not st["downgraded"] and self.actor_instance is not None:
                verdict = self._actor_fast_verdict(mname)
            if verdict is None or (verdict[0] == "gen") is not stream:
                # sticky, like the ring pump: executing later records
                # while an earlier one replays over RPC would reorder
                # the caller's calls
                st["downgraded"] = True
                await self._fast_reply_one(sink, fastpath.pack_reply(
                    tid, fastpath.NEED_SLOW, b"", seq=seq))
                return
            try:
                args, kwargs = await self._resolve_tunnel_descs(args, kwargs)
            except Exception as e:
                await self._fast_reply_one(sink, fastpath.pack_reply(
                    tid, fastpath.ERR, self._fast_pack_error(e), seq=seq))
                return
            await self._fast_exec_dispatched(
                sink, tid, mname, "gen" if stream else verdict[0],
                verdict[1], args, kwargs, t_sub, t_pop, seq, trc,
                "tunnel")
            return
        # plain task record ("Q"/"R"/"P"/"S")
        tid, func_id, args, kwargs, t_sub, trc = fastpath.unpack_task(rec)
        t_sub = self._tunnel_t_sub(t_sub, t_pop)
        try:
            fn = await self._load_function(func_id)
        except Exception:
            fn = None
        if (fn is None or inspect.iscoroutinefunction(fn)
                or inspect.isgeneratorfunction(fn)
                or inspect.isasyncgenfunction(fn)):
            # not fast-executable here: the driver resubmits over RPC
            # with the full budget (NEED_SLOW is a migration, not a loss)
            await self._fast_reply_one(sink, fastpath.pack_reply(
                tid, fastpath.NEED_SLOW, b""))
            return
        try:
            args, kwargs = await self._resolve_tunnel_descs(args, kwargs)
        except Exception as e:
            await self._fast_reply_one(sink, fastpath.pack_reply(
                tid, fastpath.ERR, self._fast_pack_error(e)))
            return

        def run():
            # one-task-per-worker, same as the ring pump's inline exec
            with self._exec_mutex:
                if chaos.ENABLED:
                    chaos.point("worker.exec",
                                name=getattr(fn, "__name__", "task"),
                                fast=1)
                if self._trace_on:  # sampled: span; else suppress
                    with self._fast_exec_span(
                            trc, tid, getattr(fn, "__name__", "task"),
                            "tunnel"):
                        return fn(*args, **(kwargs or {}))
                return fn(*args, **(kwargs or {}))

        t_x0 = time.perf_counter_ns()
        try:
            val = await self.core.loop.run_in_executor(self.executor, run)
            ok = True
        except BaseException as e:  # noqa: BLE001 — reply on
            ok, val = False, e
        t_x1 = time.perf_counter_ns()
        stamp = (fastpath.pack_stamp(max(0, t_pop - t_sub),
                                     max(0, t_x0 - t_pop), t_x1 - t_x0)
                 if t_sub else b"")
        rep = self._fast_pack_result(
            tid, ok, val, self.cfg.fastpath_inline_result_max, stamp,
            node=self.node_id.binary(), trace=trc)
        await self._fast_reply_one(sink, rep)

    def _fast_actor_pump_cycle(self, ring, state: dict):
        """ONE pump cycle, ON the actor's single executor thread: pop a
        batch (short blocking wait — a record arriving mid-wait wakes
        immediately), execute the methods INLINE (we ARE the actor
        thread, so state affinity is identical to the RPC path and no
        cross-thread handoff is paid), reply, then resubmit this cycle to
        the executor so queued RPC-path jobs get the thread between
        cycles (their added latency is bounded by the pop timeout).

        Once ANY record proves ineligible, every subsequent record is
        NEED_SLOWed too (sticky downgrade): executing later ring records
        while an earlier one replays over RPC would reorder the caller's
        calls — replies stream back in ring order, so the driver requeues
        the whole tail in FIFO order (and then retires the lane, closing
        this ring, which ends the cycling)."""
        from ray_tpu.core import fastpath

        try:
            if self._exit_requested:
                self._fast_pump_close(ring)
                state["closed"] = True
                state["parked"].set()
                return
            recs = ring.pop_batch(fastpath.SUB,
                                  timeout_ms=self._PUMP_HOT_POP_MS)
            if recs is None:
                self._fast_pump_close(ring)  # driver closed/retired
                state["closed"] = True
                state["parked"].set()
                return
            if recs:
                state["idle"] = 0
                if not self._fast_actor_exec_batch(ring, state, recs):
                    self._fast_pump_close(ring)
                    state["closed"] = True
                    state["parked"].set()
                    return
            else:
                state["idle"] += 1
                if state["idle"] >= self._PUMP_IDLE_CYCLES:
                    state["parked"].set()  # hand back to the keeper thread
                    return
            self.executor.submit(self._fast_actor_pump_cycle, ring, state)
        except RuntimeError:
            # executor shut down mid-resubmit (worker exit)
            self._fast_pump_close(ring)
            state["closed"] = True
            state["parked"].set()
        except BaseException:  # noqa: BLE001 — never leave the ring open
            self._fast_pump_close(ring)
            state["closed"] = True
            state["parked"].set()
            raise

    def _fast_pump_close(self, ring):
        for i, r in enumerate(self._fast_rings):
            if r is ring:
                del self._fast_rings[i]
                break
        ring.close_pair()

    def _fast_pump(self, ring, loop):
        """Pump thread: pop task records, execute, reply in one framed
        push. No asyncio, no sockets — see fastpath.py.

        Normal tasks execute INLINE on this thread rather than hopping to
        the task executor: on a single-core host each thread handoff
        measured ~100us — more than the task itself. Normal tasks are
        stateless by contract (only actors own thread-affine state), so
        thread identity is not observable; execution stays one-at-a-time
        per worker because this worker's fast records all flow through
        this one pump."""
        from ray_tpu.core import fastpath

        # completion records inline results up to the fast-lane threshold
        # (not max_inline_object_size): above it the value is sealed into
        # shm ONCE and every read is zero-copy, instead of being copied
        # through the ring and unpacked from a bytes round-trip
        inline_max = self.cfg.fastpath_inline_result_max
        fast_funcs: dict[bytes, object] = {}
        from ray_tpu.utils import recorder as _rec

        rec_r = _rec.get_recorder()  # None when the recorder is disabled
        # hot-path locals: per-record attribute walks add up at ring rate
        import struct as _struct

        clock = time.perf_counter_ns
        stamp_pack = fastpath._STAMP.pack  # raw; clamp fallback below
        pack_stamp = fastpath.pack_stamp
        wt_n = 0  # W_TASK shm slots are taken every 16th task (Dapper
        #           sampling: the per-batch POP/PUSH events plus sampled
        #           task slots keep postmortems representative at a
        #           sixteenth of the write cost)

        def load(func_id):
            fn = fast_funcs.get(func_id)
            if fn is not None:
                return fn
            try:
                fut = asyncio.run_coroutine_threadsafe(
                    self._load_function(func_id), loop)
                fn = fut.result(15)  # raylint: disable=RT020 -- cache miss: once per func_id, amortized
            except Exception:
                fast_funcs[func_id] = False
                return False
            if (not callable(fn)
                    or inspect.iscoroutinefunction(fn)
                    or inspect.isgeneratorfunction(fn)
                    or inspect.isasyncgenfunction(fn)):
                fn = False  # needs the RPC path (streaming/async machinery)
            fast_funcs[func_id] = fn
            return fn

        try:
            while not self._exit_requested:
                recs = ring.pop_batch(fastpath.SUB, timeout_ms=1000)
                if recs is None:
                    break  # ring closed by the driver
                if not recs:
                    continue
                replies = []
                bad_record = False
                closed = False
                contended = False
                # per-pop batch timestamps: t_prev advances past each
                # record so deser_i never charges a batch-mate's exec
                t_pop = t_prev = clock()
                if rec_r is not None:
                    rec_r.record(b"", _rec.WORKER_POP, t_pop, a0=len(recs))
                while True:
                    for rec in recs:
                        try:
                            tid, func_id, args, kwargs, t_sub, trc = (
                                fastpath.unpack_task(rec))
                        except Exception:
                            # undecodable record: without its task id there
                            # is nothing to reply to. Flush the replies of
                            # the batch-mates that ALREADY executed, then
                            # close the ring so the driver recovers only
                            # the rest — otherwise completed side effects
                            # would re-run.
                            bad_record = True
                            break
                        fn = load(func_id)
                        if not fn:
                            replies.append(fastpath.pack_reply(
                                tid, fastpath.NEED_SLOW, b""))
                            t_prev = clock()  # don't bill the (possibly
                            # 15s) function fetch to the next record's
                            # deserialize stage
                            continue
                        # _exec_mutex: an RPC-path normal task may be on the
                        # executor thread right now (the driver's quiet-lane
                        # preference is not an exclusion). Bounded acquire,
                        # NOT a blocking one: the RPC task may itself be
                        # waiting on THIS ring record (nested get on a ref
                        # buried in a container arg) — on contention reply
                        # NEED_SLOW so the driver reroutes to a free worker
                        # instead of deadlocking the lease.
                        if not self._exec_mutex.acquire(timeout=0.05):
                            contended = True
                            replies.append(fastpath.pack_reply(
                                tid, fastpath.NEED_SLOW, b""))
                            t_prev = clock()  # the 50ms acquire timeout
                            # must not surface as a phantom deserialize
                            # spike on the next record's stamp
                            continue
                        t_x0 = clock()
                        try:
                            if chaos.ENABLED:
                                # "worker.exec", fast-lane flavor: error
                                # rides the reply as this task's failure;
                                # kill dies holding buffered completions
                                chaos.point(
                                    "worker.exec", fast=1,
                                    name=getattr(fn, "__name__", "task"))
                            if self._trace_on:  # (2.1) sampled: child
                                # exec span; unsampled: suppression —
                                # both keep the contextvar right for
                                # nested .remote() from user code
                                with self._fast_exec_span(
                                        trc, tid,
                                        getattr(fn, "__name__", "task"),
                                        "ring"):
                                    ok, val = True, fn(*args, **kwargs)
                            else:
                                ok, val = True, fn(*args, **kwargs)
                        except BaseException as e:  # noqa: BLE001 — reply on
                            ok, val = False, e
                        finally:
                            self._exec_mutex.release()
                        t_x1 = clock()
                        ring_ns = t_pop - t_sub if t_sub else 0
                        deser_ns = t_x0 - t_prev
                        exec_ns = t_x1 - t_x0
                        t_prev = t_x1
                        if t_sub:
                            try:  # zero-cost try; clamp only on anomaly
                                stamp = stamp_pack(ring_ns, deser_ns,
                                                   exec_ns)
                            except _struct.error:
                                stamp = pack_stamp(ring_ns, deser_ns,
                                                   exec_ns)
                        else:
                            stamp = b""
                        replies.append(self._fast_pack_result(
                            tid, ok, val, inline_max, stamp, trace=trc))
                        if rec_r is not None:
                            wt_n += 1
                            if not (wt_n & 15):
                                rec_r.record_wtask(
                                    tid, t_x1,
                                    min(max(ring_ns, 0), 0xFFFFFFFF),
                                    min(deser_ns, 0xFFFFFFFF), exec_ns)
                    # Reply-drain coalescing: records that arrived while
                    # this batch executed join the SAME reply frame — a
                    # pipelined burst costs the driver one reply wake per
                    # merged batch, not per pop. Bounded so the first
                    # caller's results are never held hostage to a
                    # never-empty ring; and NEVER merged past a mutex-
                    # contention NEED_SLOW — the occupant may be blocked
                    # on the driver rerouting exactly these records, and
                    # each further merged record would burn another 50ms
                    # acquire timeout before the reroute signal ships.
                    if bad_record or contended or len(replies) >= 64:
                        break
                    if not ring.pending(fastpath.SUB):
                        break
                    more = ring.pop_batch(fastpath.SUB, timeout_ms=0)
                    if more is None:
                        closed = True  # still flush what already executed
                        break
                    if not more:
                        break
                    recs = more
                    t_pop = t_prev = time.perf_counter_ns()
                    if rec_r is not None:
                        rec_r.record(b"", _rec.WORKER_POP, t_pop,
                                     a0=len(recs))
                status = self._fast_push_replies(ring, replies)
                if rec_r is not None:
                    rec_r.record(b"", _rec.COMPLETION_PUSH,
                                 a0=len(replies))
                if bad_record or closed or status != 0:
                    break  # ring closed/undecodable: driver recovers
        finally:
            # on ANY exit — clean close or unexpected error — close the
            # ring so the driver's side breaks the lane and resubmits
            # in-flight tasks instead of waiting forever
            for i, r in enumerate(self._fast_rings):
                if r is ring:
                    del self._fast_rings[i]
                    break
            ring.close_pair()

    # every reply record must fit the driver's fixed pop buffer (1 MB); an
    # oversized record would wedge the ring (pop can never drain it)
    _FAST_ERR_MAX = 256 * 1024

    def _fast_pack_result(self, tid: bytes, ok: bool, val, inline_max: int,
                          stamp: bytes = b"", seq: int | None = None,
                          node: bytes | None = None, trace: bytes = b""):
        from ray_tpu.core import fastpath

        if not ok:
            return fastpath.pack_reply(tid, fastpath.ERR,
                                       self._fast_pack_error(val), stamp,
                                       seq, trace)
        try:
            meta, buffers = serialization.dumps_with_buffers(val)
            size = serialization.total_size(meta, buffers)
            if size <= inline_max:
                return fastpath.pack_reply(
                    tid, fastpath.OK, _pack_bytes(meta, buffers, size),
                    stamp, seq, trace)
            # big result: place it in the node's arena under the return oid
            # (same-node owner reads it directly; location registration is
            # the owner's migration step)
            oid = ObjectID.for_task_return(TaskID(tid), 0)
            payload = _pack_bytes(meta, buffers, size)
            if not self.core.store.contains(oid):  # retry may have stored it
                self.core.store.put_raw(oid, payload)
            # size rides in the record: the owner's location cache is
            # primed at completion time, no directory round-trip on get.
            # Tunnel lanes (cross-node owner) additionally carry the
            # sealing node id — the record IS the location registration
            return fastpath.pack_reply(
                tid, fastpath.OK_SHM,
                fastpath.pack_shm_desc(size, node) if node is not None
                else fastpath.pack_shm_size(size), stamp, seq, trace)
        except Exception as e:
            return fastpath.pack_reply(tid, fastpath.ERR,
                                       self._fast_pack_error(e), stamp,
                                       seq, trace)

    def _fast_pack_error(self, exc) -> bytes:
        payload = cloudpickle.dumps(_as_task_error(exc))
        if len(payload) > self._FAST_ERR_MAX:
            payload = cloudpickle.dumps(TaskError(
                f"{type(exc).__name__} (detail truncated: pickled error "
                f"was {len(payload)} bytes)"))
        return payload

    async def rpc_push_task_multi(self, conn, p):
        """Scatter-push handler: ONE frame carries many (corr_id, payload)
        items; each task gets its own reply frame when it finishes (ref:
        normal_task_submitter.cc PushTask pipelining — the driver amortizes
        frame/pickle/wakeup costs without batching completion).

        Contiguous runs of "simple" tasks — cached sync function, inline
        args, no runtime env / accelerator grant, plain int num_returns —
        execute in ONE executor hop: the thread handoff (~100us each way)
        would otherwise dominate sub-millisecond tasks. Execution stays
        strictly sequential (one lease = one CPU's worth of work).

        Runs on the notification dispatch path (no auto-reply), so EVERY
        item must get a reply here even when the batch machinery itself
        blows up — a stranded correlation id wedges the driver's lease."""
        items = p["items"]
        replied: set = set()
        try:
            await self._push_task_multi_inner(conn, items, replied)
        except Exception as e:
            await self._error_reply_all(conn, items, replied, e)

    async def _push_task_multi_inner(self, conn, items, replied: set):
        i = 0
        loop = asyncio.get_running_loop()
        while i < len(items):
            run = []
            while i < len(items):
                spec = items[i][1]["spec"]
                simple = (
                    isinstance(spec["num_returns"], int)
                    and not spec.get("runtime_env")
                    and not spec.get("tpu_chips")
                    and all(a[0] in ("v", "p") for a in spec["args"])
                    and all(a[0] in ("v", "p") for a in spec["kwargs"].values())
                )
                if simple:
                    fn = self._func_cache.get(spec["func_id"])
                    if fn is None:
                        try:
                            fn = await self._load_function(spec["func_id"])
                        except Exception:
                            fn = None
                    simple = fn is not None and not inspect.iscoroutinefunction(fn)
                if not simple:
                    break
                run.append((items[i][0], spec))
                i += 1
            if run:
                for _, s in run:
                    self._current_tasks.add(s["task_id"])
                    self.core.task_events.emit(
                        task_id=s["task_id"].hex(), name=s.get("name", "task"),
                        state="RUNNING", worker_id=self.worker_id.hex(),
                        node_id=self.node_id.hex(), pid=os.getpid(),
                    )
                t0 = time.monotonic()
                outcomes = await loop.run_in_executor(
                    self.executor, self._exec_simple_run, [s for _, s in run])
                per_task = (time.monotonic() - t0) / len(run)
                out = []
                for (corr, s), (ok, value) in zip(run, outcomes):
                    if ok:
                        try:
                            results = await self._store_results(
                                s["task_id"], s["num_returns"], value)
                            reply = {"results": results}
                            metrics.task_exec_seconds.observe(per_task)
                            state = "FINISHED"
                        except Exception as e:
                            reply = {"error": _as_task_error(e)}
                            state = "FAILED"
                    else:
                        reply = {"error": _as_task_error(value)}
                        state = "FAILED"
                    ev = dict(
                        task_id=s["task_id"].hex(), name=s.get("name", "task"),
                        state=state, worker_id=self.worker_id.hex(),
                        node_id=self.node_id.hex(), pid=os.getpid(),
                    )
                    if state == "FINISHED":
                        ev["duration_s"] = per_task
                    self.core.task_events.emit(**ev)
                    self._current_tasks.discard(s["task_id"])
                    out.append((corr, reply, None))
                    replied.add(corr)
                await conn.respond_multi(out)
                continue
            corr, payload = items[i]
            i += 1
            reply = await self.rpc_push_task(conn, payload)
            replied.add(corr)
            await conn.respond(corr, value=reply)

    async def rpc_push_actor_task_multi(self, conn, p):
        """Scatter-push for actor calls: dispatch every item immediately
        (the per-connection seq gates order execution for sync actors;
        async actors keep their concurrency), reply per item as each
        finishes.

        Contiguous consecutive-seq runs of "simple" calls — sync method on
        a max_concurrency=1 actor, default concurrency group, inline args —
        execute in ONE executor hop, like the normal-task fast path. Only
        when the actor is strictly serial anyway: on a wider pool two sync
        methods may legitimately rendezvous across threads, and batching
        them onto one thread would deadlock that."""
        items = p["items"]
        replied: set = set()
        try:
            await self._push_actor_multi_inner(conn, items, replied)
        except Exception as e:
            await self._error_reply_all(conn, items, replied, e)

    async def _push_actor_multi_inner(self, conn, items, replied: set):
        loop = asyncio.get_running_loop()
        i = 0
        serial_actor = (
            self.actor_instance is not None
            and getattr(self, "_actor_max_concurrency", 1) == 1
            and not self._group_execs
        )
        while i < len(items):
            run = []
            while serial_actor and i < len(items):
                spec = items[i][1]["spec"]
                ok = (
                    isinstance(spec.get("num_returns"), int)
                    and spec.get("seq") is not None
                    and not spec.get("concurrency_group")
                    and not self._method_groups.get(spec.get("method"))
                    and all(a[0] in ("v", "p") for a in spec["args"])
                    and all(a[0] in ("v", "p") for a in spec["kwargs"].values())
                )
                if ok:
                    m = getattr(self.actor_instance, spec["method"], None)
                    ok = (callable(m)
                          and not inspect.iscoroutinefunction(m)
                          and not inspect.isasyncgenfunction(m)
                          and not inspect.isgeneratorfunction(m))
                if ok and run:
                    ok = spec["seq"] == run[-1][1]["spec"]["seq"] + 1
                if not ok:
                    break
                run.append(items[i])
                i += 1
            if len(run) >= 2:
                # Spawn the run instead of awaiting it: a sync method in this
                # run may block until a LATER async method in the same frame
                # acts (legal on a serial actor — async methods run on the
                # loop), so the dispatch loop must keep going while the run
                # occupies the executor thread.
                for corr, _ in run:
                    replied.add(corr)  # the spawned run owns these replies
                loop.create_task(self._exec_actor_simple_run_task(conn, run))
                continue
            if run:
                corr, payload = run[0]
                replied.add(corr)  # _actor_push_respond owns the reply
                loop.create_task(self._actor_push_respond(conn, corr, payload))
                continue
            corr, payload = items[i]
            i += 1
            replied.add(corr)
            loop.create_task(self._actor_push_respond(conn, corr, payload))

    async def _exec_actor_simple_run_task(self, conn, run):
        """Task wrapper for a spawned simple run: replies happen in one
        respond_multi at the end, so on any earlier failure none of the
        items have been answered — answer them all with the error."""
        try:
            await self._exec_actor_simple_run(conn, run, set())
        except Exception as e:
            await self._error_reply_all(conn, run, set(), e)

    async def _error_reply_all(self, conn, items, replied: set, e: Exception):
        """Answer every not-yet-replied item of a multi-push frame with the
        same error; stop on a dead connection (driver handles the loss)."""
        err_reply = {"error": _as_task_error(e)}
        for corr, _ in items:
            if corr in replied:
                continue
            try:
                await conn.respond(corr, value=err_reply)
            except Exception:
                break

    async def _exec_actor_simple_run(self, conn, run, replied: set):
        gate = self._seq_gates.setdefault(conn, {"next": 0, "events": {}})
        s0 = run[0][1]["spec"]["seq"]
        while gate["next"] != s0:
            ev = gate["events"].setdefault(s0, asyncio.Event())
            await ev.wait()
        specs = [payload["spec"] for _, payload in run]
        for s in specs:
            self.core.task_events.emit(
                task_id=s["task_id"].hex(), name=s.get("method", "actor_task"),
                state="RUNNING", worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid(),
                actor_id=self.actor_id.hex() if self.actor_id else None,
            )
        # open the gate BEFORE executing, exactly like the single-dispatch
        # path releases it after dispatch: later calls (notably async
        # methods, which run on the loop even on a max_concurrency=1 actor)
        # must be able to start while this run occupies the executor thread
        # — a sync method blocking on something an async method will set
        # would otherwise deadlock. Later SYNC calls still serialize behind
        # this run in the single executor thread.
        last = specs[-1]["seq"]
        gate["next"] = last + 1
        ev = gate["events"].pop(last + 1, None)
        if ev is not None:
            ev.set()
        t0 = time.monotonic()
        outcomes = await asyncio.get_running_loop().run_in_executor(
            self.executor, self._exec_actor_run_thread, specs)
        per_task = (time.monotonic() - t0) / len(specs)
        out = []
        for (corr, _), s, (ok, value) in zip(run, specs, outcomes):
            if ok:
                try:
                    results = await self._store_results(
                        s["task_id"], s["num_returns"], value)
                    reply = {"results": results}
                    metrics.task_exec_seconds.observe(per_task)
                    state = "FINISHED"
                except Exception as e:
                    reply = {"error": _as_task_error(e)}
                    state = "FAILED"
            else:
                reply = {"error": _as_task_error(value)}
                state = "FAILED"
            ev = dict(
                task_id=s["task_id"].hex(), name=s.get("method", "actor_task"),
                state=state, worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid(),
                actor_id=self.actor_id.hex() if self.actor_id else None,
            )
            if state == "FINISHED":
                ev["duration_s"] = per_task
            self.core.task_events.emit(**ev)
            out.append((corr, reply, None))
            replied.add(corr)
        await conn.respond_multi(out)

    def _traced_call(self, spec, fn, args, kwargs):
        """Run a user callable inside a child span when the spec carries a
        trace context (ref: tracing_helper.py:36-60 — child spans around
        execution; the contextvar makes nested .remote() calls chain)."""
        if chaos.ENABLED:
            # "worker.exec", RPC-path flavor: `error` raises here and
            # becomes this task's TaskError; `kill` SIGKILLs the worker
            # mid-task (owner retries); `delay` stretches the execution
            chaos.point("worker.exec",
                        name=spec.get("name") or spec.get("method", "task"))
        tc = spec.get("trace_ctx")
        if not tc:
            if self._trace_on:  # unsampled request: inherit the decision
                with _TraceSuppress():
                    return fn(*args, **kwargs)
            return fn(*args, **kwargs)
        from ray_tpu.utils import tracing

        name = spec.get("name") or spec.get("method", "task")
        with tracing.span(f"{name}::run", tc, self._span_sink(spec),
                          stage="exec", transport="rpc"):
            return fn(*args, **kwargs)

    async def _traced_acall(self, spec, coro_fn, args, kwargs):
        """Async twin of _traced_call for coroutine tasks/actor methods."""
        if chaos.ENABLED:
            chaos.point("worker.exec",
                        name=spec.get("name") or spec.get("method", "task"))
        tc = spec.get("trace_ctx")
        if not tc:
            if self._trace_on:  # unsampled request: inherit the decision
                with _TraceSuppress():
                    return await coro_fn(*args, **kwargs)
            return await coro_fn(*args, **kwargs)
        from ray_tpu.utils import tracing

        name = spec.get("name") or spec.get("method", "task")
        with tracing.span(f"{name}::run", tc, self._span_sink(spec),
                          stage="exec", transport="rpc"):
            return await coro_fn(*args, **kwargs)

    def _span_sink(self, spec):
        def sink(s):
            self.core.task_events.emit(
                task_id=spec["task_id"].hex(), name=s["name"], state="SPAN",
                span=s, worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid())
        return sink

    def _fast_span_sink(self, tid: bytes):
        """Span sink for fast-lane records (raw task-id bytes instead of
        a spec dict) — built only for SAMPLED records, so the allocation
        never rides the unsampled path."""
        def sink(s):
            self.core.task_events.emit(
                task_id=tid.hex(), name=s["name"], state="SPAN",
                span=s, worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid())
        return sink

    def _fast_exec_span(self, trc: bytes, tid: bytes, name: str,
                        transport: str):
        """Child span around one sampled fast-lane record's execution:
        the record's wire leg is the parent (the driver's pre-minted
        ::call span, so exec nests inside the wire interval), the
        contextvar activates so nested ``.remote()`` calls from user
        code chain into the same trace across any number of processes.

        For an UNTRACED record under tracing-on (trc empty: the
        submitter decided unsampled), returns a suppression guard
        instead — nested submits inherit the unsampled decision rather
        than re-drawing a root mid-request."""
        from ray_tpu.utils import tracing

        if not trc:
            return _TraceSuppress()
        return tracing.span(f"{name}::run", tracing.unpack_ctx(trc),
                            self._fast_span_sink(tid), stage="exec",
                            transport=transport)

    def _exec_actor_run_thread(self, specs):
        out = []
        inst = self.actor_instance
        for spec in specs:
            try:
                m = getattr(inst, spec["method"])
                args = [
                    serialization.unpack(a[1]) if a[0] == "v" else a[1]
                    for a in spec["args"]
                ]
                kwargs = {
                    k: serialization.unpack(a[1]) if a[0] == "v" else a[1]
                    for k, a in spec["kwargs"].items()
                }
                out.append((True, self._traced_call(spec, m, args, kwargs)))
            except Exception as e:
                out.append((False, e))
        return out

    async def _actor_push_respond(self, conn, corr, payload):
        try:
            reply = await self.rpc_push_actor_task(conn, payload)
            await conn.respond(corr, value=reply)
        except Exception as e:
            try:
                await conn.respond(corr, error=e)
            except (rpc.RpcError, OSError):
                pass  # caller hung up: nobody is owed this error

    def _exec_simple_run(self, run):
        """Thread-side body of the simple-batch fast path: no awaits, no
        loop interaction — just call the user functions back to back."""
        out = []
        with self._exec_mutex:  # exclude concurrent ring-pump inline exec
            for spec in run:
                try:
                    fn = self._func_cache[spec["func_id"]]
                    args = [
                        serialization.unpack(a[1]) if a[0] == "v" else a[1]
                        for a in spec["args"]
                    ]
                    kwargs = {
                        k: serialization.unpack(a[1]) if a[0] == "v" else a[1]
                        for k, a in spec["kwargs"].items()
                    }
                    value = self._traced_call(spec, fn, args, kwargs)
                    if inspect.isgenerator(value):
                        value = list(value)
                        if spec["num_returns"] != 1:
                            value = tuple(value)
                    out.append((True, value))
                except Exception as e:
                    out.append((False, e))
        return out

    async def rpc_push_task(self, conn, p):
        spec = p["spec"]
        self._current_tasks.add(spec["task_id"])
        try:
            self._apply_accel_env(spec.get("tpu_chips"))
            await self._apply_runtime_env(spec.get("runtime_env"))
            fn = await self._load_function(spec["func_id"])
            args = await self._fetch_args(spec["args"])
            kwargs = dict(zip(spec["kwargs"].keys(), await self._fetch_args(list(spec["kwargs"].values()))))
            self.core.task_events.emit(
                task_id=spec["task_id"].hex(), name=spec.get("name", "task"),
                state="RUNNING", worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid(),
            )
            t0 = time.monotonic()
            if spec["num_returns"] == "streaming":
                return await self._execute_streaming(spec, fn, args, kwargs)
            loop = asyncio.get_running_loop()
            if inspect.iscoroutinefunction(fn):
                value = await self._traced_acall(spec, fn, args, kwargs)
            else:
                def _run_locked():
                    with self._exec_mutex:  # one task per worker
                        out = self._traced_call(spec, fn, args, kwargs)
                        if inspect.isgenerator(out):
                            # legacy generator semantics (ref: old
                            # num_returns=N generators): materialize
                            # UNDER the mutex — the user code is the
                            # generator body, not the call that made it
                            return list(out), True
                        return out, False

                value, was_gen = await loop.run_in_executor(
                    self.executor, _run_locked)
                if was_gen and spec["num_returns"] != 1:
                    value = tuple(value)  # N>1 distributes the items
            results = await self._store_results(spec["task_id"], spec["num_returns"], value)
            dur = time.monotonic() - t0
            metrics.task_exec_seconds.observe(dur)
            self.core.task_events.emit(
                task_id=spec["task_id"].hex(), name=spec.get("name", "task"),
                state="FINISHED", worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid(), duration_s=dur,
            )
            return {"results": results}
        except Exception as e:
            self.core.task_events.emit(
                task_id=spec["task_id"].hex(), name=spec.get("name", "task"),
                state="FAILED", worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid(),
            )
            return {"error": _as_task_error(e)}
        finally:
            self._current_tasks.discard(spec["task_id"])

    async def _execute_streaming(self, spec, fn, args, kwargs, executor=None):
        """Run a (sync or async) generator, reporting each item to the
        owner as it is produced (ref: _raylet.pyx:1363
        execute_streaming_generator_sync/async; item report RPC
        core_worker.proto:498).

        A sync generator occupies ONE executor job for its entire run (a
        driver thread iterating it), preserving the one-method-at-a-time
        actor invariant — other method calls cannot interleave between
        yields on a max_concurrency=1 actor. Backpressure: the driver
        thread blocks on a small semaphore window that the sender releases
        per owner ack (the generator_waiter.h role)."""
        task_id = spec["task_id"]
        task_name = spec.get("name") or spec.get("method", "stream")
        owner = await rpc.connect(*spec["owner_address"], timeout=10)
        loop = asyncio.get_running_loop()
        index = 0
        t0 = time.monotonic()
        try:
            gen = fn(*args, **kwargs)
            if inspect.isasyncgen(gen):
                async def items():
                    async for v in gen:
                        yield v

                item_iter = items()
                release = lambda: None  # noqa: E731  (async gen self-paces)
            elif inspect.isgenerator(gen):
                import threading

                window = threading.Semaphore(2)
                out_q: asyncio.Queue = asyncio.Queue()
                ctl = {"stop": False}

                def drive():
                    try:
                        for v in gen:
                            window.acquire()
                            if ctl["stop"]:
                                gen.close()  # runs GeneratorExit on THIS thread
                                break
                            loop.call_soon_threadsafe(out_q.put_nowait, ("item", v))
                        loop.call_soon_threadsafe(out_q.put_nowait, ("end", None))
                    except BaseException as e:  # noqa: BLE001
                        loop.call_soon_threadsafe(out_q.put_nowait, ("error", e))

                driver = loop.run_in_executor(executor or self.executor, drive)

                async def items():
                    while True:
                        kind, v = await out_q.get()
                        if kind == "item":
                            yield v
                        elif kind == "error":
                            raise v
                        else:
                            await driver
                            return

                async def cancel():
                    ctl["stop"] = True
                    window.release()
                    await driver

                item_iter = items()
                release = window.release
            else:
                raise TaskError(
                    "num_returns='streaming' requires a generator function"
                )
            if inspect.isasyncgen(gen):
                async def cancel():  # noqa: F811
                    try:
                        await gen.aclose()
                    except Exception:
                        log.debug("async generator close failed",
                                  exc_info=True)
            async for value in item_iter:
                item = await self._pack_item(task_id, index, value)
                reply = await owner.call(
                    "generator_item", {"task_id": task_id, "index": index, "item": item}
                )
                index += 1
                release()
                if not reply.get("ok"):
                    await cancel()  # consumer dropped the generator
                    break
            await owner.call("generator_item", {"task_id": task_id, "done": True})
            dur = time.monotonic() - t0
            metrics.task_exec_seconds.observe(dur)
            self.core.task_events.emit(
                task_id=task_id.hex(), name=task_name, state="FINISHED",
                worker_id=self.worker_id.hex(), node_id=self.node_id.hex(),
                pid=os.getpid(), duration_s=dur, items=index,
            )
            return {"results": [], "streaming": True, "count": index}
        except Exception as e:
            err = _as_task_error(e)
            self.core.task_events.emit(
                task_id=task_id.hex(), name=task_name, state="FAILED",
                worker_id=self.worker_id.hex(), node_id=self.node_id.hex(),
                pid=os.getpid(),
            )
            try:
                await owner.call(
                    "generator_item", {"task_id": task_id, "done": True, "error": err}
                )
            except (rpc.RpcError, OSError):
                pass  # owner gone: the stream dies with its consumer
            return {"error": err}
        finally:
            await owner.close()

    async def _pack_item(self, task_id, index: int, value) -> dict:
        """Serialize one yielded item: small inline, large via shm +
        location registration (same split as _store_results)."""
        meta, buffers = serialization.dumps_with_buffers(value)
        size = serialization.total_size(meta, buffers)
        if size <= self.cfg.max_inline_object_size:
            return {"inline": _pack_bytes(meta, buffers, size)}
        oid = ObjectID.for_task_return(task_id, index)
        await self._store_shm_object(oid, meta, buffers)
        return {"shm": True, "size": size, "node": self.node_id.binary()}

    async def _store_shm_object(self, oid, meta, buffers):
        """Seal a large value into local shm and register this node as a
        holder in the GCS object directory (shared by task returns and
        streamed items)."""
        size = serialization.total_size(meta, buffers)
        if self.core.spill_pressure(size):
            try:  # free arena by spill, not eviction (local_object_manager.h)
                await self.core.raylet.call("spill_now", {"need": size})
            except (rpc.RpcError, OSError):
                pass  # advisory: create() below retries under pressure
        from ray_tpu.core.object_store import ObjectStoreFullError

        for attempt in range(5):
            try:
                buf = self.core.store.create(oid, size)
                break
            except ObjectStoreFullError:
                # arena full of pinned data: give spills / reader releases
                # a beat instead of failing the task on transient pressure
                if attempt == 4:
                    raise
                try:
                    await self.core.raylet.call("spill_now", {"need": size})
                except (rpc.RpcError, OSError):
                    pass  # advisory: the backoff retry still runs
                await asyncio.sleep(0.2 * (attempt + 1))
        serialization.pack_into(meta, buffers, buf)
        self.core.store.seal(oid)
        import pickle

        holders_blob = await self.core.gcs.call("kv_get", {"ns": "obj_loc", "key": oid.hex()})
        holders = pickle.loads(holders_blob) if holders_blob else set()
        holders.add(self.node_id.binary())
        await self.core.gcs.call(
            "kv_put", {"ns": "obj_loc", "key": oid.hex(), "value": pickle.dumps(holders)}
        )

    # --------------------------------------------------------------- actors
    async def rpc_create_actor(self, conn, p):
        spec = p["spec"]
        self._apply_accel_env(p.get("tpu_chips"))
        await self._apply_runtime_env(spec.get("runtime_env"))
        cls = cloudpickle.loads(spec["class_blob"])
        args = await self._fetch_args(spec["args"])
        kwargs = dict(zip(spec["kwargs"].keys(), await self._fetch_args(list(spec["kwargs"].values()))))
        max_concurrency = spec.get("max_concurrency", 1)
        self._actor_max_concurrency = max_concurrency
        if max_concurrency > 1:
            self.executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=max_concurrency, thread_name_prefix="rt-actor"
            )
        # named concurrency groups: each group gets its own executor pool +
        # async-slot semaphore, isolated from the default executor
        # (ref: concurrency_group_manager.cc per-group thread pools)
        self._method_groups = spec.get("method_groups") or {}
        self._group_execs = {}
        self._group_sems = {}
        for gname, slots in (spec.get("concurrency_groups") or {}).items():
            slots = max(1, int(slots))
            self._group_execs[gname] = concurrent.futures.ThreadPoolExecutor(
                max_workers=slots, thread_name_prefix=f"rt-cg-{gname}"
            )
            self._group_sems[gname] = asyncio.Semaphore(slots)
        loop = asyncio.get_running_loop()
        try:
            self.actor_instance = await loop.run_in_executor(
                self.executor, lambda: cls(*args, **kwargs)
            )
        except Exception as e:
            raise _as_task_error(e) from None
        self.actor_id = spec["actor_id"]
        # fast-lane method eligibility, resolved ONCE per actor lifetime
        # (the ring pump and the attach reply both read it; see
        # _build_actor_method_table)
        self._actor_method_table = self._build_actor_method_table(cls)
        return {"ok": True}

    async def rpc_push_actor_task(self, conn, p):
        """Executes an actor call with per-caller-connection FIFO ordering
        (ref: actor_scheduling_queue.cc sequence gating): the seq gate is
        held through arg fetching and work dispatch, then released before
        awaiting the result — sync methods serialize through the executor
        thread, async methods start in order but run concurrently."""
        spec = p["spec"]
        if self.actor_instance is None:
            return {"error": TaskError("no actor instance on this worker")}
        seq = spec.get("seq")
        gate = self._seq_gates.setdefault(conn, {"next": 0, "events": {}})
        if seq is not None:
            while gate["next"] != seq:
                ev = gate["events"].setdefault(seq, asyncio.Event())
                await ev.wait()
        work = None
        streaming = spec.get("num_returns") == "streaming"
        try:
            method = getattr(self.actor_instance, spec["method"])
            args = await self._fetch_args(spec["args"])
            kwargs = dict(zip(spec["kwargs"].keys(), await self._fetch_args(list(spec["kwargs"].values()))))
            group = (spec.get("concurrency_group")
                     or self._method_groups.get(spec["method"]))
            if group and group not in self._group_execs:
                # loud, not a silent fallback: an undeclared group name
                # (typo) would otherwise lose the isolation it asked for
                return {"error": TaskError(
                    f"concurrency group {group!r} not declared on this actor "
                    f"(declared: {sorted(self._group_execs)})")}
            if streaming:
                # a grouped generator drives its iteration on the group's
                # pool, not the default executor (isolation holds for
                # streaming methods too)
                work = asyncio.get_running_loop().create_task(
                    self._execute_streaming(
                        spec, method, args, kwargs,
                        executor=self._group_execs.get(group))
                )
            elif inspect.iscoroutinefunction(method):
                if group and group in self._group_sems:
                    sem = self._group_sems[group]

                    async def run_grouped(method=method, args=args, kwargs=kwargs):
                        async with sem:  # group-bounded async slots
                            return await self._traced_acall(
                                spec, method, args, kwargs)

                    work = asyncio.get_running_loop().create_task(run_grouped())
                else:
                    work = asyncio.get_running_loop().create_task(
                        self._traced_acall(spec, method, args, kwargs))
            else:
                loop = asyncio.get_running_loop()
                executor = self._group_execs.get(group, self.executor)
                work = loop.run_in_executor(
                    executor,
                    lambda: self._traced_call(spec, method, args, kwargs))
        except Exception as e:
            return {"error": _as_task_error(e)}
        finally:
            if seq is not None:
                gate["next"] = seq + 1
                ev = gate["events"].pop(seq + 1, None)
                if ev is not None:
                    ev.set()
        self.core.task_events.emit(
            task_id=spec["task_id"].hex(), name=spec.get("method", "actor_task"),
            state="RUNNING", worker_id=self.worker_id.hex(),
            node_id=self.node_id.hex(), pid=os.getpid(),
            actor_id=self.actor_id.hex() if self.actor_id else None,
        )
        t0 = time.monotonic()
        try:
            value = await work
            if streaming:
                return value  # _execute_streaming builds the full reply
            results = await self._store_results(spec["task_id"], spec["num_returns"], value)
            dur = time.monotonic() - t0
            metrics.task_exec_seconds.observe(dur)
            self.core.task_events.emit(
                task_id=spec["task_id"].hex(), name=spec.get("method", "actor_task"),
                state="FINISHED", worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid(), duration_s=dur,
            )
            return {"results": results}
        except Exception as e:
            self.core.task_events.emit(
                task_id=spec["task_id"].hex(), name=spec.get("method", "actor_task"),
                state="FAILED", worker_id=self.worker_id.hex(),
                node_id=self.node_id.hex(), pid=os.getpid(),
            )
            return {"error": _as_task_error(e)}

    async def rpc_start_dag_loop(self, conn, p):
        """Run a compiled-DAG static schedule until its channels close
        (ref: compiled_dag_node.py actor loop). Dedicated thread: blocking
        channel waits must not stall the actor's normal method surface."""
        if self.actor_instance is None:
            return {"error": TaskError("no actor instance on this worker")}
        from ray_tpu.dag.runner import run_dag_loop

        loop = asyncio.get_running_loop()
        ex = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rt-dag"
        )
        try:
            result = await loop.run_in_executor(
                ex, lambda: run_dag_loop(self, p["schedule"])
            )
            return {"result": result}
        except Exception as e:
            return {"error": _as_task_error(e)}
        finally:
            ex.shutdown(wait=False)

    async def rpc_dump_stack(self, conn, p):
        """On-demand stack capture of every thread in this worker (ref:
        dashboard/modules/reporter/profile_manager.py:82 — there py-spy
        attaches externally; here the worker self-reports, which needs no
        ptrace capability and works in containers)."""
        import threading

        names = {t.ident: t.name for t in threading.enumerate()}
        out = [{
            "thread_id": tid,
            "name": names.get(tid, "?"),
            "stack": "".join(traceback.format_stack(frame)),
        } for tid, frame in sys._current_frames().items()]
        return {"pid": os.getpid(), "worker_id": self.worker_id.hex(),
                "threads": out}

    async def rpc_cpu_profile(self, conn, p):
        """Sampled CPU profile of this worker: walk every thread's stack
        at a fixed interval for duration_s and aggregate FOLDED stacks
        (root;child;leaf -> sample count) — the flamegraph input format
        (ref: profile_manager.py:82, where py-spy record produces
        speedscope output externally; here the worker samples itself, so
        no ptrace and no subprocess). state.get_cpu_profile renders the
        folded map as speedscope JSON."""
        import threading
        import time as _time

        duration = min(float(p.get("duration_s", 2.0)), 30.0)
        interval = max(float(p.get("interval_s", 0.01)), 0.001)

        def sample():
            folded: dict[str, int] = {}
            samples = 0
            me = threading.get_ident()
            end = _time.monotonic() + duration
            while _time.monotonic() < end:
                for tid, frame in sys._current_frames().items():
                    if tid == me:
                        continue
                    parts = []
                    f = frame
                    while f is not None:
                        code = f.f_code
                        parts.append(
                            f"{code.co_name} "
                            f"({os.path.basename(code.co_filename)}"
                            f":{f.f_lineno})")
                        f = f.f_back
                    key = ";".join(reversed(parts))
                    folded[key] = folded.get(key, 0) + 1
                samples += 1
                _time.sleep(interval)
            return folded, samples

        folded, samples = await asyncio.get_running_loop().run_in_executor(
            None, sample)
        return {"pid": os.getpid(), "worker_id": self.worker_id.hex(),
                "duration_s": duration, "interval_s": interval,
                "samples": samples, "folded": folded}

    async def rpc_heap_profile(self, conn, p):
        """On-demand heap profiling via tracemalloc (the memray role of
        the reference's profile_manager.py:191, reimplemented in-process:
        no external profiler attach, works in containers).

        action="start" begins tracing (nframes deep); "snapshot" returns
        the top-N allocation sites grouped by traceback since start;
        "stop" ends tracing and frees the bookkeeping."""
        import tracemalloc

        action = p.get("action", "snapshot")
        if action == "start":
            if not tracemalloc.is_tracing():
                tracemalloc.start(int(p.get("nframes", 8)))
            return {"tracing": True}
        if action == "stop":
            tracemalloc.stop()
            return {"tracing": False}
        if not tracemalloc.is_tracing():
            return {"error": "not tracing: call action='start' first"}
        snap = tracemalloc.take_snapshot()
        top = snap.statistics("traceback")[: int(p.get("top", 20))]
        stats = [{
            "size_bytes": s.size,
            "count": s.count,
            "traceback": s.traceback.format(),
        } for s in top]
        current, peak = tracemalloc.get_traced_memory()
        return {"pid": os.getpid(), "worker_id": self.worker_id.hex(),
                "current_bytes": current, "peak_bytes": peak,
                "top": stats}

    async def rpc_exit_worker(self, conn, p):
        self._exit_requested = True
        from ray_tpu.utils import recorder as _recorder

        rec = _recorder.get_recorder() if self.cfg.recorder_enabled else None
        if rec is not None:
            rec.unlink()  # clean exit: no postmortem, don't leak the file
        if _profiler is not None:  # RT_WORKER_PROFILE_DIR diagnosis mode
            _profiler.disable()
            _profiler.dump_stats(os.path.join(
                os.environ["RT_WORKER_PROFILE_DIR"],
                f"worker-{os.getpid()}.prof"))
        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return True

    async def rpc_ping(self, conn, p):
        return {"pid": os.getpid(), "actor": self.actor_id}


class _TraceSuppress:
    """Guard installing tracing.UNSAMPLED around one UNTRACED record's
    execution when tracing is enabled cluster-wide: head sampling is per
    request, so nested ``.remote()`` calls from an unsampled request's
    user code inherit the decision instead of re-drawing a fresh root
    mid-request. Duck-types the span interface the dispatch path's
    manual enter/exit handling expects (``_token``)."""

    __slots__ = ("_token",)

    def __init__(self):
        self._token = None

    def __enter__(self):
        from ray_tpu.utils import tracing

        self._token = tracing.suppress()
        return self

    def __exit__(self, exc_type, exc, tb):
        from ray_tpu.utils import tracing

        tracing.deactivate(self._token)
        self._token = None
        return False


class _TunnelSink:
    """Reply-side face of one worker tunnel lane: duck-types the reply
    half of a ring for ``_fast_reply_one``/``_fast_pack_result`` — framed
    completion records buffer here (any thread: the loop's dispatched
    execs AND the executor's inline batches) and every reply landing in
    the same loop tick coalesces into ONE ``tunnel_replies`` notify back
    through the raylet (the worker-side half of the tunnel's frame
    coalescing). ``_desc_node`` makes OK_SHM results carry this node's
    id (the cross-node location descriptor)."""

    __slots__ = ("_w", "_st", "_desc_node", "_lock")

    def __init__(self, worker: "Worker", st: dict):
        import threading as _threading

        self._w = worker
        self._st = st
        self._desc_node = worker.node_id.binary()
        self._lock = _threading.Lock()

    def push_batch(self, which: int, framed: bytes, timeout_ms: int = 0) -> int:
        st = self._st
        if st.get("closed"):
            return -7  # closed: the driver's break-lane recovery owns it
        with self._lock:
            st["reply_buf"].append(bytes(framed))
            arm = not st["reply_armed"]
            if arm:
                st["reply_armed"] = True
        if arm:
            loop = self._w.core.loop
            try:
                import threading as _threading

                if _threading.get_ident() == getattr(loop, "_thread_id",
                                                     None):
                    loop.call_soon(self._flush)
                else:
                    loop.call_soon_threadsafe(self._flush)
            except RuntimeError:
                return -7  # loop gone (worker exit)
        return len(framed)

    def push_raw(self, which: int, framed: bytes, timeout_ms: int = -1) -> int:
        return 0 if self.push_batch(which, framed, timeout_ms) >= 0 else -7

    def _flush(self):
        st = self._st
        with self._lock:
            buf = st["reply_buf"]
            if not buf:
                st["reply_armed"] = False
                return
            st["reply_buf"] = []
        data = buf[0] if len(buf) == 1 else b"".join(buf)
        conn = st["conn"]
        try:
            conn.send_nowait({"k": "n", "m": "tunnel_replies",
                              "p": {"frames": [(st["lane"], data)]}})
        except Exception:
            # raylet link gone: the driver discovers the break through
            # the raylet (tunnel_down) or its health sweep; records are
            # recovered by break-lane resubmission
            st["closed"] = True
            log.debug("tunnel reply push failed", exc_info=True)
            return
        self._w.core.loop.call_soon(self._flush)  # burst linger


def _as_task_error(e: Exception) -> Exception:
    if isinstance(e, TaskError):
        return e
    if getattr(e, "_rt_error_passthrough", False):
        # typed-error contract (serve/exceptions.py): the class promises
        # to be importable + picklable everywhere, so it ships as-is and
        # callers can dispatch on the type (retry classification, proxy
        # status mapping) instead of parsing a flattened message
        return e
    tb = traceback.format_exc()
    return TaskError(f"{type(e).__name__}: {e}", cause_repr=repr(e), traceback_str=tb)


def main():
    chaos.maybe_arm()  # fault schedule rides the serialized config

    async def run():
        worker = Worker()
        await worker.start()
        await asyncio.Event().wait()

    prof_dir = os.environ.get("RT_WORKER_PROFILE_DIR")
    if prof_dir:  # perf diagnosis: dump per-worker cProfile stats at exit
        import cProfile
        import signal

        global _profiler
        _profiler = cProfile.Profile()
        _profiler.enable()

        def _dump(signum, frame):
            _profiler.disable()
            _profiler.dump_stats(
                os.path.join(prof_dir, f"worker-{os.getpid()}.prof"))
            os._exit(0)

        signal.signal(signal.SIGTERM, _dump)
    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()

"""Per-process runtime core shared by drivers and workers.

Equivalent of the reference CoreWorker (ref: src/ray/core_worker/
core_worker.h:166): owns the in-process memory store for inline objects
(memory_store.h:45), the shm-store client for large ones
(plasma_store_provider.h:93), lease-cached task submission
(normal_task_submitter.cc — leases amortized per scheduling key),
dependency resolution that inlines ready small args
(dependency_resolver.cc), direct actor-task submission with per-caller
ordering (actor_task_submitter.h:75), task retries + result tracking
(task_manager.h:175), and the owner side of object resolution: every
process serves ``get_object``/``wait_object`` for objects it owns.

All async code runs on one event loop: the driver hosts it on a background
thread (utils.rpc.EventLoopThread); workers run it as their main loop.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import sys
import time
import traceback
from dataclasses import dataclass, field
from typing import Any

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle

import pickle

from ray_tpu.config import get_config
from ray_tpu.core import object_store
from ray_tpu.core.object_store import SharedObjectStore
from ray_tpu.core.ref import (
    ActorError,
    ActorHandle,
    GetTimeoutError,
    ObjectLostError,
    ObjectRef,
    ObjectRefGenerator,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.utils import aio, metrics, rpc, serialization
from ray_tpu.utils.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID

ALIVE = "ALIVE"
DEAD = "DEAD"


@dataclass
class _MemEntry:
    value: Any = None
    packed: bytes | None = None
    error: Exception | None = None
    ready: asyncio.Event = field(default_factory=asyncio.Event)
    in_shm: bool = False  # large result living in some node's shm store


@dataclass
class _GenState:
    """Owner-side state for one streaming task (ref: task_manager.cc
    ObjectRefStream): items arrive via rpc_generator_item pushes."""

    items: list = field(default_factory=list)
    event: asyncio.Event = field(default_factory=asyncio.Event)
    done: bool = False
    error: Exception | None = None


@dataclass
class _LeasedWorker:
    lease_id: int
    address: tuple[str, int]
    worker_id: str
    raylet_address: tuple[str, int]
    conn: rpc.Connection | None = None
    busy: bool = False
    idle_since: float = field(default_factory=time.monotonic)
    tpu_chips: list | None = None  # chip ids the lease granted


@dataclass
class _SchedulingKeyState:
    """Per (func, resources) lease pool (ref: SchedulingKey in
    normal_task_submitter.h — leases are cached and reused)."""

    pending: asyncio.Queue = field(default_factory=asyncio.Queue)
    workers: list[_LeasedWorker] = field(default_factory=list)
    lease_request_inflight: bool = False
    inflight_tasks: int = 0


class _TaskEventBuffer:
    """Batches task lifecycle events and flushes them (with a metrics
    snapshot) to the GCS on an interval (ref: task_event_buffer.h:225 —
    same drop-oldest bound, fire-and-forget flush)."""

    MAX_BUFFER = 10_000

    def __init__(self, core: "CoreClient"):
        self.core = core
        self.events: list[dict] = []

    def emit(self, **ev):
        ev.setdefault("ts", time.time())
        if len(self.events) >= self.MAX_BUFFER:
            del self.events[0]  # drop-oldest: keep the newest (terminal) states
        self.events.append(ev)

    async def _flush_loop(self):
        interval = self.core.cfg.task_events_report_interval_s
        while not self.core._closed:
            await asyncio.sleep(interval)
            await self.flush()

    async def flush(self):
        if self.core.gcs is None or self.core.gcs._closed:
            return
        try:
            if self.events:
                batch, self.events = self.events, []
                await self.core.gcs.notify("report_task_events", {"events": batch})
            # metrics publish is independent of task activity (a put-only
            # process still reports its counters)
            await self.core.gcs.call(
                "kv_put",
                {"ns": "metrics", "key": self.core.worker_id.hex(),
                 "value": pickle.dumps(metrics.registry().snapshot())},
            )
        except Exception:
            pass


class CoreClient:
    def __init__(self, loop: asyncio.AbstractEventLoop | None = None):
        self.cfg = get_config()
        self.loop = loop or asyncio.get_event_loop()
        self.worker_id = WorkerID.generate()
        self.job_id: JobID | None = None

        self.gcs: rpc.Connection | None = None
        self.raylet: rpc.Connection | None = None
        self.raylet_address: tuple[str, int] | None = None
        self.node_id: NodeID | None = None
        self.store: SharedObjectStore | None = None
        self.server = rpc.RpcServer("127.0.0.1", 0)
        self.server.add_routes(self)
        self.address: tuple[str, int] | None = None

        self.memory_store: dict[ObjectID, _MemEntry] = {}
        self.sched_keys: dict[tuple, _SchedulingKeyState] = {}
        self._func_cache: dict[bytes, Any] = {}
        self._registered_funcs: set[bytes] = set()
        self._actor_info: dict[ActorID, dict] = {}
        self._actor_conns: dict[ActorID, rpc.Connection] = {}
        self._actor_conn_locks: dict[ActorID, asyncio.Lock] = {}
        self._actor_queues: dict[ActorID, list] = {}
        self._actor_pump_running: set[ActorID] = set()
        self._conn_seq: dict[rpc.Connection, int] = {}
        self._subscribed_actors: set[ActorID] = set()
        self._task_counter = 0
        self._gen_states: dict[TaskID, _GenState] = {}
        self._closed = False
        self._bg = aio.TaskGroup()
        self.task_events = _TaskEventBuffer(self)

    # ----------------------------------------------------------- bootstrap
    async def connect(self, gcs_address: tuple[str, int], raylet_address: tuple[str, int]):
        self.address = await self.server.start()
        self.gcs = await rpc.connect(*gcs_address, timeout=self.cfg.rpc_connect_timeout_s)
        self.gcs.on_message = self._on_push
        self.raylet = await rpc.connect(*raylet_address, timeout=self.cfg.rpc_connect_timeout_s)
        self.raylet_address = raylet_address
        info = await self.raylet.call("register_client", {})
        self.node_id = info["node_id"]
        self.store = SharedObjectStore(info["store_name"])
        self.job_id = await self.gcs.call("register_job", {})
        self._bg.spawn(self.task_events._flush_loop(), self.loop)

    # -------------------------------------------------------------- pubsub
    def _on_push(self, msg):
        if msg.get("m") != "pubsub":
            return
        channel = msg["p"]["channel"]
        message = msg["p"]["message"]
        if channel.startswith("actor:"):
            actor_id = ActorID.from_hex(channel.split(":", 1)[1])
            self._actor_info[actor_id] = message

    # ----------------------------------------------------------- ownership
    def on_owned_ref_deleted(self, oid: ObjectID):
        """Called from ObjectRef.__del__ on the owner: drop the local value.
        (Round-1 GC: owner-local release; distributed borrow counting is a
        later-round refinement — shm copies remain until LRU eviction.)"""
        if self._closed:
            return
        try:
            self.loop.call_soon_threadsafe(self._free_object, oid)
        except RuntimeError:
            pass

    def _free_object(self, oid: ObjectID):
        self.memory_store.pop(oid, None)

    # ----------------------------------------------------------------- put
    def put_value(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        meta, buffers = serialization.dumps_with_buffers(value)
        size = serialization.total_size(meta, buffers)
        metrics.objects_put.inc()
        metrics.object_bytes_put.inc(size)
        entry = _MemEntry()
        if size <= self.cfg.max_inline_object_size:
            entry.packed = _pack_bytes(meta, buffers, size)
            self.memory_store[oid] = entry
            entry.ready.set()
        else:
            buf = self.store.create(oid, size)
            serialization.pack_into(meta, buffers, buf)
            self.store.seal(oid)
            entry.in_shm = True
            self.memory_store[oid] = entry
            entry.ready.set()
            self._call_on_loop(self._register_location(oid))
        return ObjectRef(oid, self.address, _core=self)

    async def _register_location(self, oid: ObjectID):
        holders = {self.node_id.binary()}
        await self.gcs.call(
            "kv_put", {"ns": "obj_loc", "key": oid.hex(), "value": pickle.dumps(holders)}
        )

    # ----------------------------------------------------------------- get
    async def get_async(self, refs: list[ObjectRef], timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            out.append(await self._get_one(ref, deadline))
        return out

    async def _get_one(self, ref: ObjectRef, deadline: float | None):
        oid = ref.id
        while True:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(f"get timed out on {ref}")
            entry = self.memory_store.get(oid)
            if entry is not None and entry.ready.is_set():
                if entry.error is not None:
                    raise entry.error
                if not entry.in_shm:
                    if entry.packed is not None:
                        return serialization.unpack(entry.packed)
                    return entry.value
                # owned shm result — may live on the executing node's store
                # (spillback): fall through to the shm/pull path below
            if self.store.contains(oid):
                try:
                    return await self.loop.run_in_executor(None, self.store.get, oid, 10_000)
                except object_store.ObjectEvictedError:
                    # Local copy was LRU-evicted under memory pressure between
                    # contains() and get(): re-pull from another holder (the
                    # raylet consults the GCS directory); no holder → lost.
                    ok = await self.raylet.call("pull_object", {"object_id": oid.binary()})
                    if not ok:
                        raise ObjectLostError(
                            f"{ref} was evicted and no other copy exists"
                        ) from None
                    continue
            if entry is not None:
                if entry.ready.is_set():  # owned, in_shm, not local: pull it
                    ok = await self.raylet.call("pull_object", {"object_id": oid.binary()})
                    if not ok:
                        # distinguish "not there yet" from "gone": a local
                        # eviction tombstone + no pullable holder means the
                        # object is lost, not late
                        if self.store.is_evicted(oid):
                            raise ObjectLostError(
                                f"{ref} was evicted and no other copy exists"
                            )
                        await asyncio.sleep(0.05)
                    continue
                # owned, pending task result
                await _wait_event(entry.ready, remaining)
                continue
            # borrowed ref: ask the owner
            if ref.owner_address is None or tuple(ref.owner_address) == self.address:
                await asyncio.sleep(0.01)
                continue
            try:
                reply = await self._owner_call(
                    ref, "get_object", {"object_id": oid.binary()}, remaining
                )
            except asyncio.TimeoutError:
                raise GetTimeoutError(f"get timed out on {ref}") from None
            if reply.get("error") is not None:
                raise reply["error"]
            if reply.get("inline") is not None:
                return serialization.unpack(reply["inline"])
            # large object: pull into local shm through our raylet
            ok = await self.raylet.call("pull_object", {"object_id": oid.binary()})
            if not ok:
                await asyncio.sleep(0.05)
                continue

    async def _owner_call(self, ref: ObjectRef, method: str, payload: dict,
                          timeout: float | None):
        conn = await rpc.connect(*ref.owner_address, timeout=self.cfg.rpc_connect_timeout_s)
        try:
            return await conn.call(method, payload, timeout=timeout)
        finally:
            await conn.close()

    # ---------------------------------------------------------------- wait
    async def wait_async(self, refs, num_returns, timeout, fetch_local=True):
        pending = list(refs)
        ready: list = []
        deadline = None if timeout is None else time.monotonic() + timeout

        async def is_ready(ref) -> bool:
            entry = self.memory_store.get(ref.id)
            if entry is not None:
                return entry.ready.is_set()
            if self.store.contains(ref.id):
                return True
            if ref.owner_address and tuple(ref.owner_address) != self.address:
                try:
                    r = await self._owner_call(
                        ref, "probe_object", {"object_id": ref.id.binary()}, 5.0
                    )
                    if r and fetch_local:
                        # start moving the payload to this node in the
                        # background (ref: ray.wait fetch_local semantics)
                        self.loop.create_task(
                            self.raylet.call("pull_object", {"object_id": ref.id.binary()})
                        )
                    return bool(r)
                except Exception:
                    return False
            return False

        while True:
            still = []
            for ref in pending:
                if len(ready) < num_returns and await is_ready(ref):
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns or not pending:
                return ready, pending
            if deadline is not None and time.monotonic() >= deadline:
                return ready, pending
            await asyncio.sleep(0.005)

    # -------------------------------------------- owner-side object service
    async def rpc_get_object(self, conn, p):
        oid = ObjectID(p["object_id"])
        entry = self.memory_store.get(oid)
        if entry is None:
            if self.store is not None and self.store.contains(oid):
                return {"shm": True}
            return {"error": TaskError(f"object {oid} unknown to owner (freed?)")}
        await entry.ready.wait()
        if entry.error is not None:
            return {"error": entry.error}
        if entry.in_shm:
            return {"shm": True}
        if entry.packed is not None:
            return {"inline": entry.packed}
        meta, buffers = serialization.dumps_with_buffers(entry.value)
        return {"inline": _pack_bytes(meta, buffers, serialization.total_size(meta, buffers))}

    async def rpc_probe_object(self, conn, p):
        oid = ObjectID(p["object_id"])
        entry = self.memory_store.get(oid)
        if entry is not None:
            return entry.ready.is_set()
        return self.store is not None and self.store.contains(oid)

    # ------------------------------------------------------ task submission
    def _register_function(self, fn) -> bytes:
        """Export the function blob to the GCS function table once
        (ref: remote_function.py pickled-function export). Registration is
        fire-and-forget: executors retry the table fetch briefly, so a task
        can never race ahead of its own function blob for long."""
        cached = getattr(fn, "__rt_func_id__", None)
        if cached is not None and cached in self._registered_funcs:
            return cached
        blob = serialization.ship_dumps(fn)
        func_id = hashlib.sha1(blob).digest()
        if func_id not in self._registered_funcs:
            self._call_on_loop(
                self.gcs.call(
                    "kv_put",
                    {"ns": "funcs", "key": func_id.hex(), "value": blob, "overwrite": False},
                )
            )
            self._registered_funcs.add(func_id)
        try:
            fn.__rt_func_id__ = func_id
        except (AttributeError, TypeError):
            pass
        return func_id

    def submit_task(self, fn, args, kwargs, *, num_returns=1, resources=None,
                    max_retries=None, placement_group=None, bundle_index=-1,
                    scheduling_node=None, name=None) -> list[ObjectRef] | ObjectRef:
        """Synchronous entry (driver thread) or loop-thread entry (nested)."""
        func_id = self._register_function(fn)
        self._task_counter += 1
        task_id = TaskID.generate()
        resources = dict(resources or {"CPU": 1.0})
        spec = {
            "task_id": task_id,
            "name": name or getattr(fn, "__name__", "task"),
            "func_id": func_id,
            "args": args,
            "kwargs": kwargs,
            "num_returns": num_returns,
            "resources": resources,
            "owner_address": self.address,
            "max_retries": self.cfg.default_max_task_retries if max_retries is None else max_retries,
            "placement_group": placement_group,
            "bundle_index": bundle_index,
            "scheduling_node": scheduling_node,
        }
        metrics.tasks_submitted.inc()
        self.task_events.emit(task_id=task_id.hex(), name=spec["name"],
                              state="PENDING_ARGS_AVAIL")
        if num_returns == "streaming":
            self._gen_states[task_id] = _GenState()
            self._call_on_loop(self._submit_async(spec))
            return ObjectRefGenerator(task_id, self)
        refs = []
        for i in range(num_returns):
            roid = ObjectID.for_task_return(task_id, i)
            self.memory_store[roid] = _MemEntry()
            refs.append(ObjectRef(roid, self.address, _core=self))
        self._call_on_loop(self._submit_async(spec))
        return refs[0] if num_returns == 1 else refs

    def _call_on_loop(self, coro):
        if _in_loop(self.loop):
            self._bg.spawn(coro, self.loop)
        else:
            self.loop.call_soon_threadsafe(self._bg.spawn, coro, self.loop)

    async def _submit_async(self, spec: dict):
        try:
            spec["args"] = await self._resolve_args(spec["args"])
            spec["kwargs"] = dict(
                zip(spec["kwargs"].keys(), await self._resolve_args(list(spec["kwargs"].values())))
            )
        except Exception as e:
            self._complete_task_error(spec, e)
            return
        key = (
            spec["func_id"],
            tuple(sorted(spec["resources"].items())),
            spec.get("placement_group") and spec["placement_group"].hex(),
            spec.get("bundle_index"),
            spec.get("scheduling_node"),
        )
        state = self.sched_keys.setdefault(key, _SchedulingKeyState())
        state.inflight_tasks += 1
        await state.pending.put(spec)
        await self._pump(key, state)

    async def _resolve_args(self, args):
        """Dependency resolution (ref: dependency_resolver.cc): owned inline
        args become values; everything else ships as a ref descriptor the
        executor fetches."""
        out = []
        for a in args:
            if isinstance(a, ObjectRef):
                entry = self.memory_store.get(a.id)
                if entry is not None:
                    await entry.ready.wait()
                    if entry.error is not None:
                        raise entry.error
                    if not entry.in_shm:
                        packed = entry.packed
                        if packed is None:
                            meta, bufs = serialization.dumps_with_buffers(entry.value)
                            packed = _pack_bytes(meta, bufs, serialization.total_size(meta, bufs))
                        out.append(("v", packed))
                        continue
                out.append(("r", a.id.binary(), a.owner_address))
            else:
                # pack through our serializer (cloudpickle fallback, jax/numpy
                # out-of-band) — the raw rpc frame uses plain pickle which
                # would choke on closures/jax values
                out.append(("v", serialization.pack(a)))
        return out

    async def _pump(self, key, state: _SchedulingKeyState):
        """Dispatch pending tasks onto free leased workers; grow leases."""
        # hand tasks to free workers
        free = [w for w in state.workers if not w.busy]
        while free and not state.pending.empty():
            w = free.pop()
            spec = state.pending.get_nowait()
            w.busy = True
            self._bg.spawn(self._run_on_worker(key, state, w, spec), self.loop)
        if not state.pending.empty() and not state.lease_request_inflight:
            state.lease_request_inflight = True
            self._bg.spawn(self._request_lease(key, state), self.loop)

    async def _request_lease(self, key, state: _SchedulingKeyState):
        try:
            resources = dict(key[1])
            pg_hex = key[2]
            payload = {
                "resources": resources,
                "pg_id": None,
                "bundle_index": key[3],
            }
            if pg_hex:
                from ray_tpu.utils.ids import PlacementGroupID

                payload["pg_id"] = PlacementGroupID.from_hex(pg_hex)
            raylet_addr = self.raylet_address
            target_node = key[4]
            if target_node is not None:
                payload["no_spill"] = True
                raylet_addr = tuple(target_node)
            for _ in range(16):  # follow spillback chain
                conn = (
                    self.raylet
                    if tuple(raylet_addr) == tuple(self.raylet_address)
                    else await rpc.connect(*raylet_addr)
                )
                try:
                    # persistent conn → raylet may reap the lease if we die
                    payload["owner_bound"] = conn is self.raylet
                    reply = await conn.call("lease_worker", payload)
                finally:
                    if conn is not self.raylet:
                        await conn.close()
                if reply.get("granted"):
                    w = _LeasedWorker(
                        lease_id=reply["lease_id"],
                        address=tuple(reply["worker_address"]),
                        worker_id=reply["worker_id"],
                        raylet_address=tuple(raylet_addr),
                        tpu_chips=reply.get("tpu_chips"),
                    )
                    w.conn = await rpc.connect(*w.address)
                    state.workers.append(w)
                    break
                raylet_addr = tuple(reply["spill_to"])
        except Exception:
            traceback.print_exc()
        finally:
            state.lease_request_inflight = False
            await self._pump(key, state)

    async def _run_on_worker(self, key, state, w: _LeasedWorker, spec: dict):
        self.task_events.emit(task_id=spec["task_id"].hex(), name=spec["name"],
                              state="SUBMITTED_TO_WORKER", worker_id=w.worker_id)
        try:
            if w.tpu_chips:
                spec["tpu_chips"] = w.tpu_chips
            reply = await w.conn.call("push_task", {"spec": spec})
        except rpc.ConnectionLost:
            await self._on_worker_lost(key, state, w, spec)
            return
        except Exception as e:
            # e.g. an unpicklable task spec: fail the task, free the worker
            self._complete_task_error(spec, e)
            state.inflight_tasks -= 1
            w.busy = False
            w.idle_since = time.monotonic()
            await self._pump(key, state)
            return
        self._apply_task_reply(spec, reply)
        state.inflight_tasks -= 1
        w.busy = False
        w.idle_since = time.monotonic()
        await self._pump(key, state)
        self._bg.spawn(self._maybe_return_lease(key, state, w), self.loop)

    def _apply_task_reply(self, spec, reply):
        task_id = spec["task_id"]
        name = spec.get("name") or spec.get("method", "task")
        if reply.get("error") is not None:
            metrics.tasks_finished.inc(tags={"outcome": "failed"})
            self.task_events.emit(task_id=task_id.hex(), name=name, state="FAILED",
                                  error=str(reply["error"])[:200])
            self._complete_task_error(spec, reply["error"])
            return
        metrics.tasks_finished.inc(tags={"outcome": "ok"})
        self.task_events.emit(task_id=task_id.hex(), name=name, state="FINISHED")
        for i, result in enumerate(reply["results"]):
            oid = ObjectID.for_task_return(task_id, i)
            entry = self.memory_store.get(oid)
            if entry is None:
                continue
            if result.get("inline") is not None:
                entry.packed = result["inline"]
            else:
                entry.in_shm = True
            entry.ready.set()

    def _complete_task_error(self, spec, error):
        if not isinstance(error, Exception):
            error = TaskError(str(error))
        if spec["num_returns"] == "streaming":
            state = self._gen_states.get(spec["task_id"])
            if state is not None and not state.done:
                state.error = error
                state.done = True
                state.event.set()
            return
        for i in range(spec["num_returns"]):
            oid = ObjectID.for_task_return(spec["task_id"], i)
            entry = self.memory_store.get(oid)
            if entry is not None:
                entry.error = error
                entry.ready.set()

    # -------------------------------------------------- streaming generators
    async def rpc_generator_item(self, conn, p):
        """Executor reports one yielded item (ref: core_worker.proto:498
        ReportGeneratorItemReturns); the awaited ack is the backpressure
        (generator_waiter.h role: producer can't run far ahead)."""
        task_id = p["task_id"]
        state = self._gen_states.get(task_id)
        if state is None:
            return {"ok": False, "cancelled": True}  # consumer gone: stop
        if p.get("item") is not None:
            item = p["item"]
            oid = ObjectID.for_task_return(task_id, p["index"])
            entry = _MemEntry()
            if item.get("inline") is not None:
                entry.packed = item["inline"]
            else:
                entry.in_shm = True
            entry.ready.set()
            self.memory_store[oid] = entry
            state.items.append(ObjectRef(oid, self.address, _core=self))
        if p.get("done"):
            state.done = True
            if p.get("error") is not None:
                state.error = p["error"]
        state.event.set()
        return {"ok": True}

    async def gen_next(self, task_id: TaskID, timeout: float | None = None):
        """Next item ref, or None when the stream ends (async side)."""
        state = self._gen_states.get(task_id)
        if state is None:
            return None
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            if state.items:
                return state.items.pop(0)
            if state.error is not None:
                err = state.error
                raise err if isinstance(err, Exception) else TaskError(str(err))
            if state.done:
                return None
            state.event.clear()
            try:
                remain = (deadline - time.monotonic()) if deadline else None
                if remain is not None and remain <= 0:
                    raise GetTimeoutError(f"generator {task_id} timed out")
                await asyncio.wait_for(state.event.wait(), remain)
            except asyncio.TimeoutError:
                raise GetTimeoutError(f"generator {task_id} timed out") from None

    def gen_next_sync(self, task_id: TaskID, timeout: float | None = None):
        return self._run_sync(self.gen_next(task_id, timeout))

    def gen_completed(self, task_id: TaskID) -> bool:
        state = self._gen_states.get(task_id)
        return state is None or (state.done and not state.items)

    def gen_release(self, task_id: TaskID):
        self._gen_states.pop(task_id, None)

    async def _on_worker_lost(self, key, state, w, spec):
        """Retry on worker death (ref: task_manager.h retries). Streaming
        tasks don't replay: already-consumed items can't be un-delivered,
        so the stream fails fast instead."""
        if w in state.workers:
            state.workers.remove(w)
        if spec["num_returns"] == "streaming":
            self._complete_task_error(spec, WorkerCrashedError())
            state.inflight_tasks -= 1
            await self._pump(key, state)
            return
        spec["max_retries"] = spec.get("max_retries", 0) - 1
        if spec["max_retries"] >= 0:
            await state.pending.put(spec)
        else:
            self._complete_task_error(spec, WorkerCrashedError())
            state.inflight_tasks -= 1
        await self._pump(key, state)

    async def _maybe_return_lease(self, key, state: _SchedulingKeyState, w: _LeasedWorker):
        await asyncio.sleep(self.cfg.worker_lease_timeout_s)
        if w.busy or w not in state.workers:
            return
        if time.monotonic() - w.idle_since < self.cfg.worker_lease_timeout_s * 0.9:
            return
        state.workers.remove(w)
        try:
            if w.conn is not None:
                await w.conn.close()
            conn = (
                self.raylet
                if tuple(w.raylet_address) == tuple(self.raylet_address)
                else await rpc.connect(*w.raylet_address)
            )
            try:
                await conn.call("return_lease", {"lease_id": w.lease_id})
            finally:
                if conn is not self.raylet:
                    await conn.close()
        except Exception:
            pass

    # ------------------------------------------------------------- actors
    def _build_actor_spec(self, cls, args, kwargs, *, num_cpus=1.0, resources=None,
                          name=None, max_restarts=0, max_concurrency=1,
                          placement_group=None, bundle_index=-1,
                          get_if_exists=False, lifetime=None) -> dict:
        res = dict(resources or {})
        res.setdefault("CPU", num_cpus)
        return {
            "actor_id": ActorID.generate(),
            "name": name,
            "class_blob": serialization.ship_dumps(cls),
            "args": args,
            "kwargs": kwargs,
            "resources": res,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "placement_group": placement_group,
            "bundle_index": bundle_index,
            "owner_address": self.address,
            "get_if_exists": get_if_exists,
            "lifetime": lifetime,
        }

    async def _register_actor(self, spec: dict) -> dict:
        spec["args"] = await self._resolve_args(spec["args"])
        spec["kwargs"] = dict(
            zip(
                spec["kwargs"].keys(),
                await self._resolve_args(list(spec["kwargs"].values())),
            )
        )
        view = await self.gcs.call("register_actor", {"spec": spec})
        self._actor_info[view["actor_id"]] = view
        return view

    def create_actor(self, cls, args, kwargs, **opts) -> ActorHandle:
        spec = self._build_actor_spec(cls, args, kwargs, **opts)
        if _in_loop(self.loop):
            # Called from the event loop (e.g. an async actor creating other
            # actors): can't block. The actor_id is chosen client-side, so
            # the handle is valid immediately; registration completes in the
            # background and callers wait for ALIVE via _actor_connection.
            if spec["get_if_exists"]:
                raise RuntimeError(
                    "get_if_exists=True requires the registration reply and "
                    "cannot be used from the event-loop thread; await "
                    "create_actor_async instead"
                )
            self._bg.spawn(self._register_actor(spec), self.loop)
            return ActorHandle(spec["actor_id"], core=self)
        view = self._run_sync(self._register_actor(spec))
        return ActorHandle(view["actor_id"], core=self)

    async def create_actor_async(self, cls, args, kwargs, **opts) -> ActorHandle:
        """Event-loop-safe actor creation (supports get_if_exists)."""
        spec = self._build_actor_spec(cls, args, kwargs, **opts)
        view = await self._register_actor(spec)
        return ActorHandle(view["actor_id"], core=self)

    async def get_actor_by_name_async(self, name: str) -> ActorHandle | None:
        info = await self.gcs.call("get_actor", {"name": name})
        if info is None or info.get("state") == DEAD:
            return None
        self._actor_info[info["actor_id"]] = info
        return ActorHandle(info["actor_id"], core=self)

    def submit_actor_task(self, handle: ActorHandle, method: str, args, kwargs,
                          num_returns=1) -> ObjectRef | list[ObjectRef]:
        """Submission order is fixed here (sync, caller thread); a per-actor
        pump coroutine then resolves deps, assigns per-connection sequence
        numbers and pipelines pushes — the reference's ActorTaskSubmitter
        shape (ref: actor_task_submitter.h:75, ordered sends + out-of-order
        replies)."""
        task_id = TaskID.generate()
        actor_id = handle.actor_id
        metrics.actor_calls.inc()
        self.task_events.emit(task_id=task_id.hex(), name=method,
                              state="PENDING_ARGS_AVAIL", actor_id=actor_id.hex())
        streaming = num_returns == "streaming"
        refs = []
        if streaming:
            self._gen_states[task_id] = _GenState()
        else:
            for i in range(num_returns):
                roid = ObjectID.for_task_return(task_id, i)
                self.memory_store[roid] = _MemEntry()
                refs.append(ObjectRef(roid, self.address, _core=self))
        spec = {
            "task_id": task_id,
            "actor_id": actor_id,
            "method": method,
            "args": args,
            "kwargs": kwargs,
            "num_returns": num_returns,
            "owner_address": self.address,
            "seq": None,
        }
        q = self._actor_queues.setdefault(actor_id, [])
        q.append(spec)
        self._call_on_loop(self._ensure_actor_pump(actor_id))
        if streaming:
            return ObjectRefGenerator(task_id, self)
        return refs[0] if num_returns == 1 else refs

    async def _ensure_actor_pump(self, actor_id: ActorID):
        if actor_id in self._actor_pump_running:
            return
        self._actor_pump_running.add(actor_id)
        try:
            q = self._actor_queues.get(actor_id, [])
            while q:
                spec = q.pop(0)
                await self._dispatch_actor_task(spec)
        finally:
            self._actor_pump_running.discard(actor_id)

    async def _dispatch_actor_task(self, spec):
        try:
            spec["args"] = await self._resolve_args(spec["args"])
            spec["kwargs"] = dict(
                zip(spec["kwargs"].keys(), await self._resolve_args(list(spec["kwargs"].values())))
            )
            conn = await self._actor_connection(spec["actor_id"])
            seq = self._conn_seq.get(conn, 0)
            self._conn_seq[conn] = seq + 1
            spec["seq"] = seq
            # pipelined: don't await the reply here, keep the pump moving
            self._bg.spawn(self._await_actor_reply(conn, spec), self.loop)
        except Exception as e:
            self._complete_task_error(spec, e)

    async def _await_actor_reply(self, conn, spec):
        try:
            reply = await conn.call("push_actor_task", {"spec": spec})
            self._apply_task_reply(spec, reply)
        except rpc.ConnectionLost:
            if self._actor_conns.get(spec["actor_id"]) is conn:
                self._actor_conns.pop(spec["actor_id"], None)
            self._conn_seq.pop(conn, None)
            if spec["num_returns"] == "streaming":
                # never replay a generator: already-consumed items would
                # duplicate into the live stream (same policy as
                # _on_worker_lost for streaming tasks)
                self._complete_task_error(
                    spec, ActorError("actor connection lost mid-stream")
                )
                return
            info = await self._refresh_actor(spec["actor_id"])
            if info and info.get("state") in (ALIVE, "RESTARTING", "PENDING_CREATION"):
                spec["seq"] = None  # ordering lost across reconnect: send unordered
                await self._await_actor_reply_retry(spec)
            else:
                cause = (info or {}).get("death_cause") or "actor connection lost"
                self._complete_task_error(spec, ActorError(cause))
        except Exception as e:
            self._complete_task_error(spec, e)

    async def _await_actor_reply_retry(self, spec):
        try:
            conn = await self._actor_connection(spec["actor_id"])
            reply = await conn.call("push_actor_task", {"spec": spec})
            self._apply_task_reply(spec, reply)
        except Exception as e:
            if isinstance(e, rpc.ConnectionLost):
                e = ActorError("actor connection lost during retry")
            self._complete_task_error(spec, e)

    async def _actor_connection(self, actor_id: ActorID) -> rpc.Connection:
        lock = self._actor_conn_locks.setdefault(actor_id, asyncio.Lock())
        async with lock:
            return await self._actor_connection_locked(actor_id)

    async def _actor_connection_locked(self, actor_id: ActorID) -> rpc.Connection:
        conn = self._actor_conns.get(actor_id)
        if conn is not None and not conn._closed:
            return conn
        info = self._actor_info.get(actor_id)
        deadline = time.monotonic() + self.cfg.worker_start_timeout_s
        while True:
            while True:
                if info is not None:
                    if info.get("state") == DEAD:
                        raise ActorError(info.get("death_cause") or "actor is dead")
                    if info.get("state") == ALIVE and info.get("address"):
                        break
                if time.monotonic() > deadline:
                    raise ActorError(f"actor {actor_id} not available in time")
                if actor_id not in self._subscribed_actors:
                    self._subscribed_actors.add(actor_id)
                    await self.gcs.call("subscribe", {"channel": f"actor:{actor_id.hex()}"})
                info = await self._refresh_actor(actor_id)
                if not (info and info.get("state") == ALIVE and info.get("address")):
                    await asyncio.sleep(0.05)
                    info = self._actor_info.get(actor_id)
            try:
                conn = await rpc.connect(*info["address"], timeout=1.0)
                break
            except rpc.ConnectionLost:
                # GCS can briefly advertise ALIVE at the old address after a
                # hard crash (reaper period lag); treat as stale and keep
                # waiting for the restarted actor to publish a reachable
                # address.
                if time.monotonic() > deadline:
                    raise ActorError(f"actor {actor_id} not reachable in time")
                await asyncio.sleep(0.1)
                self._actor_info.pop(actor_id, None)
                info = None
        self._actor_conns[actor_id] = conn
        return conn

    async def _refresh_actor(self, actor_id: ActorID):
        info = await self.gcs.call("get_actor", {"actor_id": actor_id})
        if info is not None:
            self._actor_info[actor_id] = info
        return info

    def kill_actor(self, actor_id: ActorID, no_restart=True):
        self._run_sync(self.gcs.call("kill_actor", {"actor_id": actor_id,
                                                    "no_restart": no_restart}))

    def get_actor_by_name(self, name: str) -> ActorHandle | None:
        info = self._run_sync(self.gcs.call("get_actor", {"name": name}))
        if info is None or info.get("state") == DEAD:
            return None
        self._actor_info[info["actor_id"]] = info
        return ActorHandle(info["actor_id"], core=self)

    # ------------------------------------------------------ compiled DAGs
    def start_dag_loop(self, handle: ActorHandle, schedule: dict):
        """Kick off an actor's compiled-DAG loop; the RPC reply arrives when
        the loop exits at teardown (ref: compiled_dag_node.py actor loops).
        Returns a concurrent.futures.Future with the loop's summary."""

        async def go():
            conn = await self._actor_connection(handle.actor_id)
            reply = await conn.call("start_dag_loop", {"schedule": schedule},
                                    timeout=None)
            if isinstance(reply, dict) and reply.get("error") is not None:
                raise reply["error"]
            return reply.get("result") if isinstance(reply, dict) else reply

        return asyncio.run_coroutine_threadsafe(go(), self.loop)

    def wait_dag_loop(self, fut, timeout: float | None = None):
        return fut.result(timeout)

    # ------------------------------------------------------------ helpers
    def _run_sync(self, coro, timeout=None):
        if _in_loop(self.loop):
            raise RuntimeError("sync call from loop thread")
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout)

    async def close(self):
        await self.task_events.flush()
        self._closed = True
        await self._bg.cancel_all()
        # return all leases
        for key, state in self.sched_keys.items():
            for w in state.workers:
                try:
                    if w.conn:
                        await w.conn.close()
                    conn = await rpc.connect(*w.raylet_address, timeout=2)
                    await conn.call("return_lease", {"lease_id": w.lease_id})
                    await conn.close()
                except Exception:
                    pass
        for conn in self._actor_conns.values():
            await conn.close()
        await self.server.stop()
        if self.gcs:
            await self.gcs.close()
        if self.raylet:
            await self.raylet.close()
        if self.store:
            self.store.close()


def _pack_bytes(meta, buffers, size) -> bytes:
    out = bytearray(size)
    serialization.pack_into(meta, buffers, memoryview(out))
    return bytes(out)


def _in_loop(loop) -> bool:
    try:
        return asyncio.get_running_loop() is loop
    except RuntimeError:
        return False


async def _wait_event(event: asyncio.Event, timeout: float | None):
    if timeout is None:
        await event.wait()
    else:
        try:
            await asyncio.wait_for(event.wait(), timeout)
        except asyncio.TimeoutError:
            pass

"""Per-process runtime core shared by drivers and workers.

Equivalent of the reference CoreWorker (ref: src/ray/core_worker/
core_worker.h:166): owns the in-process memory store for inline objects
(memory_store.h:45), the shm-store client for large ones
(plasma_store_provider.h:93), lease-cached task submission
(normal_task_submitter.cc — leases amortized per scheduling key),
dependency resolution that inlines ready small args
(dependency_resolver.cc), direct actor-task submission with per-caller
ordering (actor_task_submitter.h:75), task retries + result tracking
(task_manager.h:175), and the owner side of object resolution: every
process serves ``get_object``/``wait_object`` for objects it owns.

All async code runs on one event loop: the driver hosts it on a background
thread (utils.rpc.EventLoopThread); workers run it as their main loop.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import inspect
import os
import random
import sys
import time
import traceback
from dataclasses import dataclass, field
from typing import Any

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    import pickle as cloudpickle

import pickle
import threading as _threading

from ray_tpu.config import get_config
from ray_tpu.core import object_store
from ray_tpu.core.object_store import SharedObjectStore
from ray_tpu.core.ref import (
    ActorError,
    ActorHandle,
    ConfigurationError,
    GetTimeoutError,
    ObjectLostError,
    ObjectRef,
    ObjectRefGenerator,
    SchedulingError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.utils import aio, metrics, recorder, rpc, serialization
from ray_tpu.utils.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID

log = logging.getLogger(__name__)

_NCPU = max(1, os.cpu_count() or 1)

ALIVE = "ALIVE"
DEAD = "DEAD"


class _RecoveryNeeded(Exception):
    """Internal pump signal: a connection died while this dispatch was
    suspended; the spec must wait for the replay to be requeued first."""


@dataclass
class _MemEntry:
    value: Any = None
    packed: bytes | None = None
    error: Exception | None = None
    ready: asyncio.Event = field(default_factory=asyncio.Event)
    in_shm: bool = False  # large result living in some node's shm store
    # promise refs only: a thread-waitable twin of `ready`, so a caller
    # thread blocked in get() resolves without a loop round trip (the
    # serve router resolves one promise per request — see
    # promise_prepass)
    t_ready: Any = None


@dataclass
class _GenState:
    """Owner-side state for one streaming task (ref: task_manager.cc
    ObjectRefStream): items arrive via rpc_generator_item pushes."""

    items: list = field(default_factory=list)
    event: asyncio.Event = field(default_factory=asyncio.Event)
    done: bool = False
    error: Exception | None = None


@dataclass
class _LeasedWorker:
    lease_id: int
    address: tuple[str, int]
    worker_id: str
    raylet_address: tuple[str, int]
    conn: rpc.Connection | None = None
    busy: bool = False
    idle_since: float = field(default_factory=time.monotonic)
    tpu_chips: list | None = None  # chip ids the lease granted
    fast_lane: object | None = None  # shm-ring lane (core/fastpath.py)
    queued: int = 0  # committed batch depth (demand accounting)


@dataclass
class _SchedulingKeyState:
    """Per (func, resources) lease pool (ref: SchedulingKey in
    normal_task_submitter.h — leases are cached and reused)."""

    pending: asyncio.Queue = field(default_factory=asyncio.Queue)
    workers: list[_LeasedWorker] = field(default_factory=list)
    lease_requests_inflight: int = 0
    inflight_tasks: int = 0
    strategy: dict | None = None  # wire form of the scheduling strategy
    affinity_addr: tuple | None = None  # cached node-affinity raylet addr
    # EWMA of observed per-task seconds: long tasks dispatch chunk=1 so
    # backlog stays visible to lease growth / spillback / the autoscaler
    avg_task_s: float = 0.0
    # monotonic ts since fast-lane backlog has been continuously high;
    # only PERSISTENT backlog grows leases (a micro-task burst drains in
    # milliseconds — spawning workers for it would eat the CPU it needs)
    fast_backlog_since: float = 0.0
    # persistent-lease-failure breaker: repeated identical errors over real
    # time with zero live workers fail the pending queue (see _request_lease)
    lease_failures: int = 0
    lease_failure_sig: str | None = None
    lease_failure_since: float = 0.0


class _TaskEventBuffer:
    """Batches task lifecycle events and flushes them (with a metrics
    snapshot) to the GCS on an interval (ref: task_event_buffer.h:225 —
    same drop-oldest bound, fire-and-forget flush)."""

    MAX_BUFFER = 10_000

    def __init__(self, core: "CoreClient"):
        self.core = core
        self.events: list[dict] = []

    def emit(self, **ev):
        ev.setdefault("ts", time.time())
        if len(self.events) >= self.MAX_BUFFER:
            del self.events[0]  # drop-oldest: keep the newest (terminal) states
        self.events.append(ev)

    async def _flush_loop(self):
        interval = self.core.cfg.task_events_report_interval_s
        while not self.core._closed:
            await asyncio.sleep(interval)
            await self.flush()

    async def flush(self):
        if self.core.gcs is None or self.core.gcs._closed:
            return
        try:
            if self.events:
                batch, self.events = self.events, []
                await self.core.gcs.notify("report_task_events", {"events": batch})
            # flight-recorder drain rides the same timer: native ring/
            # store gauges + sampled stage histograms are folded into the
            # metrics snapshot below, the latency window is published
            # beside it (all the expensive work happens HERE, 1/s — the
            # task hot path only ever appends to the recorder ring)
            self.core._publish_recorder_metrics()
            # metrics publish is independent of task activity (a put-only
            # process still reports its counters)
            await self.core.gcs.call(
                "kv_put",
                {"ns": "metrics", "key": self.core.worker_id.hex(),
                 "value": pickle.dumps(metrics.registry().snapshot())},
            )
            lat = self.core._latency_snapshot()
            if lat is not None:
                await self.core.gcs.call(
                    "kv_put",
                    {"ns": "latency", "key": self.core.worker_id.hex(),
                     "value": pickle.dumps(lat)},
                )
                # only after the put landed: a transient GCS error must
                # not permanently skip republishing this window
                self.core._lat_published = lat["count"]
            # registered extra windows (sharded plane stages, ...): each
            # source returns a {stages} snapshot or None when it has
            # nothing new since its last CONFIRMED publish
            for suffix, (fn, confirm) in list(
                    self.core._latency_sources.items()):
                snap = fn()
                if snap is not None:
                    await self.core.gcs.call(
                        "kv_put",
                        {"ns": "latency",
                         "key": f"{self.core.worker_id.hex()}.{suffix}",
                         "value": pickle.dumps(snap)},
                    )
                    if confirm is not None:
                        confirm()
        except Exception:
            # transient GCS error: this window republishes next flush
            log.debug("latency window publish failed", exc_info=True)


def _strategy_key(strategy: dict | None):
    """Hashable token for the scheduling-strategy part of a lease key
    (leases are cached per strategy: a SPREAD lease pool must not be
    reused for a node-pinned task)."""
    if not strategy:
        return None
    t = strategy["type"]
    if t == "spread":
        return ("spread",)
    if t == "node_affinity":
        return ("na", strategy["node_id"], bool(strategy.get("soft")))
    if t == "node_label":
        freeze = lambda d: tuple(sorted(
            (k, tuple(sorted(v))) for k, v in d.items()))
        return ("nl", freeze(strategy.get("hard", {})),
                freeze(strategy.get("soft", {})))
    return (t,)


def _handle_options(spec: dict) -> dict:
    """Driver-side method metadata carried on creation handles (num_returns
    from @method annotations; worker-side group routing uses the spec)."""
    return {"method_num_returns": spec.get("method_num_returns") or {}}


def _expire_future(fut) -> None:
    """fast_actor_await's timeout timer: cancel the waiter, marked so
    the await can tell a timeout from a genuine caller cancellation."""
    if not fut.done():
        fut._rt_expired = True
        fut.cancel()


class FastLaneDeclined(Exception):
    """The worker NEED_SLOWed an untracked fast actor call (stale
    method-eligibility table): the call never executed; the caller
    re-dispatches it over the RPC plane."""


class _FastStreamSink:
    """Loop-confined reorder buffer for one fast-lane stream (2.3 "G"
    records). Chunks may arrive out of order — a single chunk can spill
    over RPC while later chunks keep landing on the ring — so the sink
    buffers by per-stream chunk index and releases in order. The
    terminal reply (ordinary "A"-plane record carrying
    ``pack_stream_fin(nchunks)``) is held until every chunk below
    ``nchunks`` has been released, which restores the worker's emit
    order without any per-chunk seq from the lane counter.

    All mutation happens on the owner loop (pushes arrive via the
    ``_fast_wake_q`` drain), so no lock. ``dead`` flips when the
    consumer abandons the stream; pushes after that only free orphaned
    shm seals."""

    __slots__ = ("task_id", "lane", "q", "expect", "pending",
                 "fin", "fin_n", "dead")

    def __init__(self, task_id, lane):
        self.task_id = task_id
        self.lane = lane
        self.q: asyncio.Queue = asyncio.Queue()
        self.expect = 0          # next chunk index to release
        self.pending: dict = {}  # out-of-order chunks by index
        self.fin = None          # held terminal (status, payload)
        self.fin_n = None        # chunk count the terminal promised
        self.dead = False

    def push(self, status, payload) -> None:
        from ray_tpu.core import fastpath

        if status in (fastpath.CHUNK, fastpath.CHUNK_SHM):
            seq, body = payload
            if seq < self.expect or seq in self.pending:
                return  # duplicate delivery (spill-RPC timeout re-send)
            self.pending[seq] = (status, body)
            while self.expect in self.pending:
                st, b = self.pending.pop(self.expect)
                self.q.put_nowait(("chunk", st, b, self.expect))
                self.expect += 1
            self._maybe_fin()
            return
        # terminal: OK carries pack_stream_fin(nchunks) and must wait
        # for the tail chunks; ERR / NEED_SLOW / None (lane broke) end
        # the stream immediately — consumed chunks are never replayed
        if status == fastpath.OK:
            self.fin = (status, payload)
            self.fin_n = fastpath.unpack_stream_fin(payload)
            if self.fin_n is None:  # malformed fin: fail the stream
                self.fin = None
                self.q.put_nowait(("fin", None, None, None))
                return
            self._maybe_fin()
        else:
            self.q.put_nowait(("fin", status, payload, None))

    def _maybe_fin(self) -> None:
        if self.fin is not None and self.expect >= self.fin_n:
            status, payload = self.fin
            self.fin = None
            self.q.put_nowait(("fin", status, payload, None))


class ActorCallTemplate:
    """Frozen per-(handle, method) submission state — the actor-call
    analogue of api.SubmitTemplate (ref: actor_task_submitter.h:75 cached
    per-handle submission state). Everything `.remote()` used to re-derive
    per call — the packed method-key bytes, the options-eligibility
    verdict (num_returns/concurrency-group/tracing), and the lane binding
    — is resolved ONCE at the first call of an ActorMethod (which PR 2
    already made a cached per-handle object).

    Invalidation: ``lane`` is re-looked-up whenever the bound lane is
    broken or retired (worker death, reattach after restart), and dropped
    when no live lane exists — the RPC path, which stays the source of
    truth, then serves the call. ``.options()`` forks build a new
    ActorMethod and therefore a new template. Never serialized
    (ActorMethod.__getstate__ strips it)."""

    __slots__ = ("core", "actor_id", "method", "mkey", "opts_ok", "lane")


class CoreClient:
    def __init__(self, loop: asyncio.AbstractEventLoop | None = None,
                 client_mode: bool = False):
        self.cfg = get_config()
        self.client_mode = client_mode  # remote driver: no local shm arena
        self.loop = loop or asyncio.get_event_loop()
        self.worker_id = WorkerID.generate()
        self.job_id: JobID | None = None

        self.gcs: rpc.Connection | None = None
        self.raylet: rpc.Connection | None = None
        self.raylet_address: tuple[str, int] | None = None
        self.node_id: NodeID | None = None
        self.store: SharedObjectStore | None = None
        self.server = rpc.make_server("127.0.0.1", 0)
        self.server.add_routes(self)
        self.address: tuple[str, int] | None = None

        self._store_exec = None  # lazy: see _store_executor()
        self.memory_store: dict[ObjectID, _MemEntry] = {}
        self.sched_keys: dict[tuple, _SchedulingKeyState] = {}
        self._func_cache: dict[bytes, Any] = {}
        self._registered_funcs: set[bytes] = set()
        self._actor_info: dict[ActorID, dict] = {}
        self._actor_conns: dict[ActorID, rpc.Connection] = {}
        self._actor_conn_locks: dict[ActorID, asyncio.Lock] = {}
        self._actor_queues: dict[ActorID, list] = {}
        self._actor_pump_running: set[ActorID] = set()
        # per-actor in-flight specs in send (seq) order, for FIFO replay on
        # reconnect (ref: actor_task_submitter sequence replay)
        self._actor_inflight: dict[ActorID, dict] = {}
        # dead connections awaiting pump-owned recovery, per actor
        self._actor_recover_pending: dict[ActorID, set] = {}
        self._conn_seq: dict[rpc.Connection, int] = {}
        self._subscribed_actors: set[ActorID] = set()
        # actor-death fan-out: callbacks fired (on the loop thread) when a
        # subscribed actor's pubsub view flips to DEAD — the serve router
        # and controller evict/replace replicas in ~one raylet reap tick
        # instead of waiting out a health-check period
        self._actor_death_listeners: list = []
        # owner-local actor-handle refcounting (lease-starvation fix):
        # unnamed actors created by THIS driver are auto-killed once the
        # last local handle drops and their submitted work drains, so
        # their CPU leases return to the pool instead of squatting until
        # driver exit (two sequentially created 4-actor pools used to
        # exhaust an 8-CPU node). Named/detached actors and any actor
        # whose handle was ever serialized are exempt — a shipped handle
        # may outlive every local one.
        self._actor_handle_counts: dict[ActorID, int] = {}
        self._actor_no_autokill: set[ActorID] = set()
        # placement-group state pushes ("pgs" channel, subscribed lazily
        # on the first ready()/wait): pg_id hex -> latest view, plus
        # waiter events so ready() observes PENDING→CREATED and
        # RESCHEDULING→CREATED transitions push-driven instead of polling
        self._pg_info: dict[str, dict] = {}
        self._pg_waiters: dict[str, list[asyncio.Event]] = {}
        self._pg_subscribed = False
        self._task_counter = 0
        self._cancelled_tasks: set[TaskID] = set()
        self._task_worker: dict[TaskID, tuple] = {}  # task -> (conn, worker)
        self._gen_states: dict[TaskID, _GenState] = {}
        # distributed refcounting state (ref: reference_count.h:72)
        self._local_refs: dict[ObjectID, int] = {}      # owner-side handles
        self._borrowers: dict[ObjectID, set] = {}       # owner-side registry
        self._borrow_seen: set[ObjectID] = set()        # ≥1 borrow ever landed
        self._shipped_expect: set[ObjectID] = set()     # payload-shipped refs
        self._borrowed_counts: dict[ObjectID, int] = {} # borrower-side handles
        self._shipped_at: dict[ObjectID, float] = {}
        self._owner_conns: dict[tuple, rpc.Connection] = {}
        self._owner_conn_locks: dict[tuple, asyncio.Lock] = {}
        # Completion-time location cache (ref: SURVEY §1 L0/L2 —
        # owner-resident object metadata): oid -> set of holder node ids,
        # primed by completion records / location registrations so
        # steady-state get() never consults the GCS object directory.
        # Invalidated on holder death via the "nodes" pubsub channel; the
        # directory stays the source of truth (pull falls back to it on a
        # stale hint).
        self._obj_locations: dict[ObjectID, set] = {}
        # lineage for reconstruction (ref: task_manager.h:182 lineage pinning)
        self._lineage: dict[TaskID, dict] = {}
        self._lineage_live: dict[TaskID, set] = {}  # return oids still live
        self._reconstructions: dict[ObjectID, int] = {}
        # refs pinned while their task is in flight (args must outlive
        # dispatch; ref: dependency resolver holding arg refs)
        self._inflight_pins: dict[TaskID, list] = {}
        self._ship_collect: list | None = None  # set during arg serialization
        self._rc_lock = _threading.Lock()  # counts are bumped off-loop too
        self._xq: list = []  # thread->loop submission queue (see _call_on_loop)
        self._xq_armed = False
        self._xq_linger = False
        self._xq_lazy: list = []       # deleted-ref notices (5ms timer lane)
        self._xq_lazy_armed = False
        self._xq_lock = _threading.Lock()
        self._closed = False
        self.default_runtime_env: dict | None = None  # packaged descriptor
        self._bg = aio.TaskGroup()
        self.task_events = _TaskEventBuffer(self)
        # ---- native fast path (shm task rings; see core/fastpath.py) ----
        # _fast_cv guards every map below plus each lane's inflight dict;
        # reader threads notify it once per reply batch so blocking get()s
        # resolve without touching the event loop.
        self._fast_cv = _threading.Condition()
        self._fast_lanes: list = []
        self._fast_done: dict[ObjectID, tuple] = {}   # oid -> (status, payload)
        self._fast_oid_lane: dict[ObjectID, object] = {}
        self._fast_migrate_q: list = []
        self._fast_migrate_armed = False
        self._fast_ineligible_funcs: set[bytes] = set()
        self._fast_ring_seq = 0
        self._fast_last_submit = 0  # burst detector, perf_counter_ns
        self._fast_demand_kick = 0.0  # rate-limits backlog->pump kicks
        self._fast_actor_lanes: dict[ActorID, object] = {}
        # Coalesced ring flush (see FastLane.txbuf): the flusher thread is
        # the backstop that pushes a burst's buffered tail when no
        # get()/threshold flush does; started lazily on first deferral.
        self._fast_flush_cv = _threading.Condition()
        self._fast_flush_dirty = False
        self._fast_flusher_thread: _threading.Thread | None = None
        self._fast_tx_flushes = 0   # batch pushes (stats: bench.py)
        self._fast_tx_records = 0   # records those pushes carried
        self._fast_spilled_results = 0  # completions that arrived via RPC spill
        # flight recorder (utils/recorder.py): the hot paths read this
        # cached flag (an attribute load) instead of calling
        # recorder.enabled() per task; the flush timer refreshes it
        self._rec_enabled = recorder.enabled()
        # wire-level tracing (utils/tracing.py): cached flag for the same
        # reason as _rec_enabled — the unsampled fast path pays ONE
        # attribute load + branch. _trace_pending maps a sampled in-flight
        # call's return oid to its submit-span info so reply-apply can
        # stamp the wire-level call span (bounded: sampled traffic only).
        self._trace_on = bool(self.cfg.tracing_enabled)
        self._trace_pending: dict[ObjectID, tuple] = {}
        self._rec_published = -1  # stats.n at the last metrics publish
        self._lat_published = -1  # stats.n at the last latency kv_put
        # actor-call stage window: actor fast-lane replies store their raw
        # (t0, t_rx, tid, stamp) samples here instead of the task window,
        # published beside it (ns="latency" key "<worker>.actor", stages
        # prefixed actor_*) so list_task_latency shows the actor-call
        # stage breakdown the ROADMAP item asked for
        self._actor_stats = recorder.StageStats(self.cfg.recorder_events_cap)
        self._actor_rec_published = 0   # astats.n at last metrics publish
        self._actor_lat_published = -1  # astats.n at last CONFIRMED kv_put
        self._actor_lat_pending = -1
        # extra latency windows published beside the recorder's on the
        # flush timer (ns="latency", key "<worker>.<suffix>") — the
        # sharded plane registers its shard_seal/shard_fetch/reshard
        # stage window here; list_task_latency merges every key
        self._latency_sources: dict[str, Any] = {}
        # loop-resident fast-lane waiters (the serve data plane's router
        # hop): oid -> asyncio.Future resolved DIRECTLY from the reply
        # thread with (status, payload) — skipping the migrate queue's
        # 2ms linger, which is pure added latency for a coroutine that is
        # already parked on the loop. Guarded by _fast_cv; (None, None)
        # means "the lane broke mid-flight". Resolutions ride _fast_wake_q
        # behind ONE armed drain callback with a burst linger (the
        # _drain_xq shape): a self-pipe write per reply batch measured
        # ~140µs of loop time under the syscall-intercepting sandbox —
        # at serve QPS that one wake per request was the single largest
        # loop cost.
        self._fast_loop_waiters: dict[ObjectID, asyncio.Future] = {}
        self._fast_wake_q: list = []
        self._fast_wake_armed = False
        # streaming fast lane (2.3): oid -> _FastStreamSink for live
        # streams (guarded by _fast_cv like the waiters); tombstones of
        # abandoned-but-unfinished streams so late CHUNK_SHM records
        # free their seals instead of leaking (FIFO-capped — a stream's
        # tombstone clears for good when its terminal lands)
        self._fast_stream_sinks: dict[ObjectID, Any] = {}
        self._fast_stream_dead: dict[ObjectID, Any] = {}
        # ---- cross-node node tunnels (core/tunnel.py) ----
        # TunnelClient created lazily on first remote lane; tunnel actor
        # lanes register in _fast_actor_lanes beside ring lanes and reuse
        # the whole FastLane submit/reply/recovery machinery.
        self._tunnels = None
        # revival registry: actors that ever held a tunnel lane -> their
        # node raylet address; the health loop re-attaches after a
        # tunnel break once the redial lands (dropped on actor DEAD)
        self._tunnel_actor_seen: dict[ActorID, tuple] = {}
        # descriptor pins: task_id -> ObjectRefs minted for oversized
        # tunnel args, held until the call's reply (or break) lands so
        # the sealed shm copies can't be freed mid-pull
        self._tunnel_pins: dict[TaskID, list] = {}

    # ----------------------------------------------------------- bootstrap
    async def connect(self, gcs_address: tuple[str, int], raylet_address: tuple[str, int]):
        self.address = await self.server.start()
        self.gcs_address = tuple(gcs_address)  # dialable, unlike loopback peername
        self.gcs = await rpc.connect(*gcs_address, timeout=self.cfg.rpc_connect_timeout_s)
        self.gcs.on_message = self._on_push
        self.raylet = await rpc.connect(*raylet_address, timeout=self.cfg.rpc_connect_timeout_s)
        self.raylet_address = raylet_address
        info = await self.raylet.call("register_client", {})
        self.node_id = info["node_id"]
        if self.client_mode:
            self.store = None
        else:
            try:
                self.store = SharedObjectStore(info["store_name"])
            except Exception:
                # Remote driver (Ray-Client role, ref: util/client/): the
                # raylet's shm arena is on another machine. Objects this
                # driver owns live in its memory store and are owner-served
                # over RPC; shm-resident results are fetched through the
                # raylet's chunked transfer RPCs instead of mapped.
                self.store = None
        self.job_id = await self.gcs.call("register_job", {})
        # holder-death signal for the location cache (dedicated channel:
        # "nodes" also carries per-heartbeat resource gossip this client
        # has no use for)
        try:
            await self.gcs.call("subscribe", {"channel": "node_removed"})
        except (rpc.RpcError, OSError):
            pass  # cache misses fall back to the directory anyway
        self._bg.spawn(self.task_events._flush_loop(), self.loop)
        if self.cfg.fastpath_enabled and self.store is not None:
            self._bg.spawn(self._fast_health_loop(), self.loop)
        self.add_latency_source("actor", self._actor_latency_snapshot,
                                self._actor_latency_confirm)
        # arena owners registered before the runtime came up (tiering's
        # cooperative-spill providers) get their raylet hookup now
        from ray_tpu.core import tiering

        tiering.attach_core(self)

    # -------------------------------------------------------------- pubsub
    def _on_push(self, msg):
        if msg.get("m") != "pubsub":
            return
        channel = msg["p"]["channel"]
        message = msg["p"]["message"]
        if channel.startswith("actor:"):
            actor_id = ActorID.from_hex(channel.split(":", 1)[1])
            self._actor_info[actor_id] = message
            if isinstance(message, dict) and message.get("state") == DEAD:
                self._tunnel_actor_seen.pop(actor_id, None)
                for cb in list(self._actor_death_listeners):
                    try:
                        cb(actor_id, message)
                    except Exception:
                        log.debug("actor death listener failed", exc_info=True)
        elif channel == "pgs" and isinstance(message, dict):
            pg_hex = message.get("pg_id")
            if pg_hex:
                waiters = self._pg_waiters.pop(pg_hex, None)
                if waiters:
                    # retained only while a waiter is parked (it consumes
                    # the view): no per-PG residue for the ones this
                    # driver never waits on
                    self._pg_info[pg_hex] = message
                    for evt in waiters:
                        evt.set()
        elif channel == "node_removed" and isinstance(message, dict):
            # holder died: drop it from every cached location so the next
            # get falls back to the GCS directory (source of truth)
            node_id = message.get("node_id")
            nb = node_id.binary() if hasattr(node_id, "binary") else node_id
            for oid in [o for o, holders in self._obj_locations.items()
                        if nb in holders]:
                holders = self._obj_locations[oid]
                holders.discard(nb)
                if not holders:
                    del self._obj_locations[oid]

    # ---------------------------------------------------- placement groups
    def wait_placement_group_ready(self, pg_id, timeout: float = 30.0) -> bool:
        """Block until the PG is CREATED (every bundle committed). The
        wait observes the full PG state machine: PENDING and RESCHEDULING
        keep waiting — creation or a node-death repair is in flight on
        the GCS — while REMOVED (or the timeout) returns False.
        Push-driven via the "pgs" pubsub channel, with a polling backstop
        for lost pushes (e.g. a GCS restart dropping the subscription)."""
        return self._run_sync(self._wait_pg_ready(pg_id, timeout))

    def get_placement_group_state(self, pg_id) -> dict | None:
        """Latest GCS view of one PG (state, bundle_nodes, reschedule
        cause/count); None for an unknown id."""
        return self._run_sync(
            self.gcs.call("get_placement_group", {"pg_id": pg_id}))

    async def _wait_pg_ready(self, pg_id, timeout: float) -> bool:
        if not self._pg_subscribed:
            self._pg_subscribed = True
            try:
                await self.gcs.call("subscribe", {"channel": "pgs"})
            except (rpc.RpcError, OSError):
                self._pg_subscribed = False  # degrade to pure polling
        deadline = time.monotonic() + timeout
        pg_hex = pg_id.hex()
        view = None  # pushed "pgs" state consumed after each wake
        while True:
            if view is None:
                view = await self.gcs.call(
                    "get_placement_group", {"pg_id": pg_id})
            if view is None or view["state"] == "REMOVED":
                return False
            if view["state"] == "CREATED":
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            evt = asyncio.Event()
            self._pg_waiters.setdefault(pg_hex, []).append(evt)
            try:
                await asyncio.wait_for(evt.wait(), min(remaining, 0.5))
            except asyncio.TimeoutError:
                pass  # backstop: re-poll (a GCS restart can drop pushes)
            finally:
                waiters = self._pg_waiters.get(pg_hex)
                if waiters and evt in waiters:
                    waiters.remove(evt)
                if not waiters:
                    self._pg_waiters.pop(pg_hex, None)
            # consume the pushed view; None falls back to the poll above
            view = self._pg_info.pop(pg_hex, None)

    # ----------------------------------------------------------- ownership
    # Distributed reference counting (ref: reference_count.h:72): the owner
    # frees an object's memory entry AND its shm copies (local + remote
    # holders) only when its own handles are gone, no borrower is
    # registered, and no shipment of the ref is recently in flight.

    BORROW_GRACE_S = 3.0  # covers serialize->deserialize windows
    # A shipped ref whose recipient has NEVER registered a borrow gets a
    # much longer leash: the borrow notify is an async coroutine on the
    # recipient's loop and under load (concurrent jit compiles, reply
    # bursts) it can land SECONDS late — freeing at +3s turned cached
    # disagg KV pages into "unknown to owner" for every later adopter.
    # Once any borrower registers, lifetime is governed by the borrower
    # set; this timeout only reclaims shipments whose recipient died.
    SHIP_NO_BORROW_GRACE_S = 60.0

    def note_ref_shipped(self, oid: ObjectID, ref=None,
                         expect_borrow: bool = False):
        """``expect_borrow``: the ref was pickled INSIDE a payload and will
        rehydrate as an ObjectRef at the recipient (borrow registration
        coming); spec-path arg shipments dep-resolve to values and never
        borrow, so they keep the short grace."""
        self._shipped_at[oid] = time.monotonic()
        if expect_borrow:
            self._shipped_expect.add(oid)
        col = self._ship_collect
        if col is not None and ref is not None:
            col.append(ref)  # pin the live handle for the flight

    def on_owned_ref_created(self, oid: ObjectID):
        with self._rc_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1

    def on_owned_ref_deleted(self, oid: ObjectID):
        if self._closed:
            return
        try:
            # rides the coalesced thread->loop queue: dropping a batch of
            # refs (every `get([...])` return) must not pay one self-pipe
            # write syscall per ref
            self._call_on_loop(oid)
        except RuntimeError:
            pass

    def _on_owned_ref_deleted_on_loop(self, oid: ObjectID):
        with self._rc_lock:
            n = self._local_refs.get(oid, 1) - 1
            if n > 0:
                self._local_refs[oid] = n
                return
            self._local_refs.pop(oid, None)
        # fast path: un-borrowed, un-shipped, non-shm objects free inline —
        # no coroutine spawn on the put/drop hot path
        if not self._borrowers.get(oid) and oid not in self._shipped_at:
            entry = self.memory_store.get(oid)
            if entry is None or not entry.in_shm:
                self.memory_store.pop(oid, None)
                self._release_lineage_for(oid)
                return
        self._bg.spawn(self._maybe_free_object(oid), self.loop)

    def _release_lineage_for(self, oid: ObjectID):
        tid = oid.task_id()
        live = self._lineage_live.get(tid)
        if live is not None:
            live.discard(oid)
            if not live:
                self._lineage.pop(tid, None)
                self._lineage_live.pop(tid, None)
                # nothing can reconstruct this task anymore — safe to forget
                # its cancellation mark (bounds _cancelled_tasks growth)
                self._cancelled_tasks.discard(tid)

    async def _maybe_free_object(self, oid: ObjectID):
        while not self._closed:
            if self._local_refs.get(oid, 0) > 0:
                return  # resurrected (e.g. deserialized again on the owner)
            if self._borrowers.get(oid):
                return  # an unborrow will re-trigger the free check
            shipped = self._shipped_at.get(oid)
            if shipped is not None:
                # payload-shipped ref whose borrower has NEVER registered:
                # the recipient's borrow notify may still be queued behind
                # a loaded loop — hold the object for the long leash,
                # re-checking so a landed borrow parks the free immediately
                grace = (self.SHIP_NO_BORROW_GRACE_S
                         if (oid in self._shipped_expect
                             and oid not in self._borrow_seen)
                         else self.BORROW_GRACE_S)
                wait = grace - (time.monotonic() - shipped)
                if wait > 0:  # a borrow registration may still be in flight
                    await asyncio.sleep(min(wait, 1.0))
                    continue
            break
        if self._closed:
            return
        self._shipped_at.pop(oid, None)
        self._borrowers.pop(oid, None)
        self._borrow_seen.discard(oid)
        self._shipped_expect.discard(oid)
        self._obj_locations.pop(oid, None)
        entry = self.memory_store.pop(oid, None)
        # lineage pins its task's arg refs only while some return is live
        self._release_lineage_for(oid)
        if entry is not None and entry.in_shm:
            await self._free_shm_everywhere(oid)

    async def _free_shm_everywhere(self, oid: ObjectID):
        """Delete the sealed copies on every holder node and drop the
        directory entry (the owner-driven release the reference does via
        LocalObjectManager free batches)."""
        try:
            blob = await self.gcs.call("kv_get", {"ns": "obj_loc", "key": oid.hex()})
            holders = pickle.loads(blob) if blob else set()
            if not holders and self.node_id is not None:
                # a put followed by an immediate last-ref drop can race
                # its own _register_location kv_put: the directory reads
                # empty and the sealed local copy would leak forever.
                # The owner's node is always a candidate holder — include
                # it so the local delete lands regardless.
                holders = {self.node_id.binary()}
            await self.gcs.call("kv_del", {"ns": "obj_loc", "key": oid.hex()})
            nodes = {tuple(n["address"]): n["node_id"].binary() if hasattr(n["node_id"], "binary") else n["node_id"]
                     for n in await self.gcs.call("get_cluster", {})}
            for addr, node_bin in nodes.items():
                if node_bin in holders:
                    try:
                        conn = (self.raylet if addr == tuple(self.raylet_address)
                                else await rpc.connect(*addr, timeout=2))
                        try:
                            await conn.call("delete_object", {"object_id": oid.binary()})
                        finally:
                            if conn is not self.raylet:
                                await conn.close()
                    except (rpc.RpcError, OSError):
                        pass  # holder already gone: nothing left to delete
        except Exception:
            log.debug("free() fanout failed", exc_info=True)

    # ------------------------------------------------------- borrower side
    def on_borrowed_ref_created(self, oid: ObjectID, owner_address):
        with self._rc_lock:
            n = self._borrowed_counts.get(oid, 0)
            self._borrowed_counts[oid] = n + 1
        if n == 0:
            self._call_on_loop(self._send_borrow(oid, tuple(owner_address), True))

    def on_borrowed_ref_deleted(self, oid: ObjectID, owner_address):
        if self._closed:
            return
        try:
            self.loop.call_soon_threadsafe(
                self._on_borrowed_deleted_on_loop, oid, owner_address
            )
        except RuntimeError:
            pass

    def _on_borrowed_deleted_on_loop(self, oid: ObjectID, owner_address):
        with self._rc_lock:
            n = self._borrowed_counts.get(oid, 1) - 1
            if n > 0:
                self._borrowed_counts[oid] = n
                return
            self._borrowed_counts.pop(oid, None)
        self._bg.spawn(self._send_borrow(oid, tuple(owner_address), False), self.loop)

    async def _send_borrow(self, oid: ObjectID, owner_address, borrow: bool):
        """Borrow/unborrow travel on one cached connection per owner, with
        connect+send under a per-owner lock so they arrive in order."""
        if not borrow:
            # if we recently re-shipped this borrowed ref to a third
            # process, hold our registration until its borrow can land
            shipped = self._shipped_at.pop(oid, None)
            if shipped is not None:
                wait = self.BORROW_GRACE_S - (time.monotonic() - shipped)
                if wait > 0:
                    await asyncio.sleep(wait)
        lock = self._owner_conn_locks.setdefault(owner_address, asyncio.Lock())
        try:
            async with lock:
                conn = self._owner_conns.get(owner_address)
                if conn is None or conn._closed:
                    conn = await rpc.connect(*owner_address, timeout=5)
                    self._owner_conns[owner_address] = conn
                await conn.notify(
                    "borrow_object" if borrow else "unborrow_object",
                    {"object_id": oid.binary(), "borrower": self.worker_id.hex()},
                )
        except (rpc.RpcError, OSError):
            pass  # owner died: its ref counts died with it

    # --------------------------------------------------------- owner RPCs
    async def rpc_borrow_object(self, conn, p):
        oid = ObjectID(p["object_id"])
        if oid not in self.memory_store:
            # the object is already gone (freed, or never ours): tracking
            # this borrower would create a zombie entry no free path ever
            # clears — the borrower's get surfaces the loss itself
            return False
        self._borrowers.setdefault(oid, set()).add(p["borrower"])
        self._borrow_seen.add(oid)
        return True

    async def rpc_unborrow_object(self, conn, p):
        oid = ObjectID(p["object_id"])
        holders = self._borrowers.get(oid)
        if holders is not None:
            holders.discard(p["borrower"])
            if not holders and self._local_refs.get(oid, 0) == 0:
                self._bg.spawn(self._maybe_free_object(oid), self.loop)
        return True

    # ----------------------------------------- cooperative tiering routes
    async def rpc_arena_spill_candidates(self, conn, p):
        """The raylet asks this process's registered arena owners (prefix
        cache, shard plane, staging trackers — core/tiering.py) for cold
        REFERENCED objects it may trade to tier-1."""
        from ray_tpu.core import tiering

        return tiering.collect_candidates(
            int(p.get("need", 0)),
            float(p.get("cold_after_s", self.cfg.spill_cold_after_s)))

    async def rpc_arena_spilled(self, conn, p):
        """The raylet reports candidates it actually spilled; owners stamp
        their manifest entries' (tier, path) legs."""
        from ray_tpu.core import tiering

        tiering.notify_spilled(p.get("spilled") or [])
        return True

    def register_spill_provider(self) -> None:
        """Tell the local raylet this process serves arena-owner spill
        candidates at our RPC address (idempotent raylet-side)."""
        if self.raylet is None or self.address is None:
            return
        coro = self.raylet.call("register_spill_provider",
                                {"address": list(self.address)})
        if _in_loop(self.loop):
            self._bg.spawn(coro, self.loop)
        else:
            self._run_sync(coro, timeout=10)

    def spill_objects(self, oids, timeout: float = 60.0) -> dict:
        """Explicitly spill specific sealed objects through the local
        raylet (the prefix cache's spill-not-drop eviction). Landed
        spills are fanned out to the tiering sinks (manifest tier-leg
        stamping) in BOTH modes; the returned {oid hex: {"ok", "path"}}
        map is empty when called on the event loop (the spill is spawned
        there, result delivered via the sinks) or on failure."""
        if self.raylet is None:
            return {}
        raw_ids = [o.binary() if hasattr(o, "binary") else o for o in oids]
        payload = {"object_ids": raw_ids}
        by_hex = {b.hex(): b for b in raw_ids}

        def deliver(res: dict):
            from ray_tpu.core import tiering

            tiering.notify_spilled(
                [{"object_id": by_hex[h], "path": v.get("path", "")}
                 for h, v in (res or {}).items()
                 if h in by_hex and v.get("ok")])

        async def _spill_and_deliver():
            try:
                res = await self.raylet.call("spill_objects", payload)
            except Exception:
                log.debug("spill_objects request failed", exc_info=True)
                return {}
            deliver(res)
            return res or {}

        try:
            if _in_loop(self.loop):
                self._bg.spawn(_spill_and_deliver(), self.loop)
                return {}
            return self._run_sync(_spill_and_deliver(), timeout=timeout)
        except Exception:
            log.debug("spill_objects request failed", exc_info=True)
            return {}

    def _new_owned_ref(self, oid: ObjectID) -> ObjectRef:
        self.on_owned_ref_created(oid)
        return ObjectRef(oid, self.address, _core=self)

    # -------------------------------------------------- death subscriptions
    def add_actor_death_listener(self, cb) -> None:
        """Register ``cb(actor_id, info)`` to fire (loop thread) when any
        actor this client follows transitions to DEAD. Callbacks must be
        light and non-blocking — they run inline in the pubsub push."""
        if cb not in self._actor_death_listeners:
            self._actor_death_listeners.append(cb)

    def remove_actor_death_listener(self, cb) -> None:
        try:
            self._actor_death_listeners.remove(cb)
        except ValueError:
            pass  # already removed (idempotent teardown)

    def add_latency_source(self, suffix: str, fn, confirm=None) -> None:
        """Register an extra latency window beside the flight recorder's:
        ``fn()`` returns a ``{stages: {name: [ns, ...]}}`` snapshot (or
        None when idle) and is published on the task-event flush timer
        under ns="latency" key ``<worker>.<suffix>`` —
        ``state.list_task_latency()`` merges every key in the namespace,
        so the extra stages surface with zero new API. ``confirm`` (if
        given) fires only after the kv_put LANDED, so a transient GCS
        error republishes the window next flush (the same invariant
        ``_lat_published`` keeps for the recorder's own window)."""
        self._latency_sources[suffix] = (fn, confirm)

    # -------------------------------------------------------- promise refs
    def create_promise_ref(self):
        """An owned ObjectRef whose value arrives later: returns
        ``(ref, resolve)`` where ``resolve(value=..., error=...)`` (loop
        thread only) fulfills it. The serve router's retry loop rides
        this — the caller holds ONE ordinary ref while attempts replay
        behind it; ``get``/``wait``/``await`` all work unchanged."""
        oid = ObjectID.from_random()
        entry = _MemEntry()
        entry.t_ready = _threading.Event()
        self.memory_store[oid] = entry
        ref = self._new_owned_ref(oid)

        def resolve(value=None, error: Exception | None = None):
            if error is not None:
                entry.error = error
            else:
                entry.value = value
            entry.ready.set()
            entry.t_ready.set()  # caller-thread getters (promise_prepass)

        return ref, resolve

    def promise_prepass(self, refs, timeout: float | None) -> dict:
        """Blocking wait (user thread) for promise refs: resolves them
        straight off the threading.Event twin their resolve() sets — no
        loop round trip for the get half of a serve request. Refs that
        are not promise-backed (or time out) are left for the normal get
        path. Returns {oid: ("V", value) | ("e", exc)}."""
        out: dict = {}
        deadline = None if timeout is None else time.monotonic() + timeout
        for ref in refs:
            entry = self.memory_store.get(ref.id)
            evt = getattr(entry, "t_ready", None)
            if entry is None or evt is None:
                continue
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not evt.wait(remaining):
                continue  # timed out: the slow path owns the error
            if entry.error is not None:
                out[ref.id] = ("e", entry.error)
            else:
                out[ref.id] = ("V", entry.value)
        return out

    # ----------------------------------------------------------------- put
    def put_value(self, value: Any, prefer_shm: bool = False) -> ObjectRef:
        """Store an owned object. ``prefer_shm`` forces the shm path even
        under the inline threshold (the sharded plane's shard seals: a
        shard must be arena-resident so consumers on this node read it
        zero-copy and remote nodes can pull it without an owner hop)."""
        oid = ObjectID.from_random()
        meta, buffers = serialization.dumps_with_buffers(value)
        size = serialization.total_size(meta, buffers)
        metrics.objects_put.inc()
        metrics.object_bytes_put.inc(size)
        entry = _MemEntry()
        if (size <= self.cfg.max_inline_object_size
                and not prefer_shm) or self.store is None:
            # client mode has no local shm: every owned object is memory-
            # store resident and owner-served (borrowers fetch over RPC)
            entry.packed = _pack_bytes(meta, buffers, size)
            self.memory_store[oid] = entry
            entry.ready.set()
        else:
            self._maybe_request_spill(size)
            buf = self.store.create(oid, size)
            serialization.pack_into(meta, buffers, buf)
            self.store.seal(oid)
            entry.in_shm = True
            self.memory_store[oid] = entry
            entry.ready.set()
            self._call_on_loop(self._register_location(oid))
        return self._new_owned_ref(oid)

    def spill_pressure(self, size: int) -> bool:
        """True when creating `size` more bytes would cross the spill
        threshold (shared by driver puts and worker result stores)."""
        if self.store is None or self.cfg.object_spilling_threshold <= 0:
            return False
        cap = max(1, self.store.capacity)
        return (self.store.bytes_in_use + size
                > self.cfg.object_spilling_threshold * cap)

    def _maybe_request_spill(self, size: int):
        """Pressured put: ask the raylet to spill before creating, so the
        arena frees by spill (bytes preserved on disk) instead of LRU
        eviction (bytes destroyed; a later get pays lineage re-execution).
        Ref: local_object_manager.h:42 spill-under-pressure.

        Best-effort for callers ON the event loop (async actor methods):
        the RPC is spawned rather than awaited there — the raylet's
        200ms monitor backstops the window."""
        if not self.spill_pressure(size):
            return
        try:
            if _in_loop(self.loop):
                self._bg.spawn(
                    self.raylet.call("spill_now", {"need": size}), self.loop)
            else:
                self._run_sync(
                    self.raylet.call("spill_now", {"need": size}), timeout=60)
        except Exception:
            # advisory: create() still retries under arena pressure
            log.debug("spill_now request failed", exc_info=True)

    async def _register_location(self, oid: ObjectID, holder: bytes | None = None):
        """Write the object's holder set to the GCS directory. ``holder``
        names the sealing node when it is NOT ours (tunnel completions:
        the record's shm descriptor carries the executing node)."""
        hb = holder or self.node_id.binary()
        holders = {hb}
        self._obj_locations.setdefault(oid, set()).add(hb)
        await self.gcs.call(
            "kv_put", {"ns": "obj_loc", "key": oid.hex(), "value": pickle.dumps(holders)}
        )

    async def _pull_via_raylet(self, oid: ObjectID) -> bool:
        """pull_object through the local raylet, passing the cached holder
        set as a hint so the steady-state pull skips the GCS directory
        lookup; a failed hinted pull drops the (stale) cache entry — the
        raylet already fell back to the directory inside the call."""
        payload = {"object_id": oid.binary()}
        hint = self._obj_locations.get(oid)
        if hint:
            payload["holders_hint"] = sorted(hint)
        ok = await self.raylet.call("pull_object", payload)
        if hint:
            if ok:
                # the one holder we now KNOW is our own node (the pull
                # landed locally); stale entries — e.g. a dead node the
                # raylet fell back past — drop in the same move
                self._obj_locations[oid] = {self.node_id.binary()}
            else:
                self._obj_locations.pop(oid, None)
        return ok

    async def pull_objects_batch(self, hints: dict, sizes: dict | None = None,
                                 timeout_s: float | None = None) -> dict:
        """Batched multi-object pull through the local raylet (protocol
        2.0 ``pull_objects``): ONE round trip fetches a whole
        arg/KV-manifest set into the local store, with per-object holder
        hints (location cache + caller knowledge) and exactly one GCS
        ``kv_multi_get`` raylet-side for the unhinted miss-set.
        ``hints``: {ObjectID: holder-node-id set (may be empty)}.
        ``sizes`` (optional {ObjectID: nbytes}) feeds the raylet's
        byte-budget pull admission; ``timeout_s`` (optional) is the
        admission deadline — items shed at it come back under the
        ``"_bp"`` key ({oid hex: retry_after_s}) and tier-1 restores
        under ``"_restored"``, both left in the returned map for callers
        that care. Returns {oid hex: bool} plus those side-channel keys;
        failures fall back to the per-object pull paths of the callers.
        Best effort — never raises."""
        items = []
        for oid, hint in hints.items():
            if self.store is not None and self.store.contains(oid):
                continue
            merged = set(b for b in (hint or ()) if b)
            merged |= self._obj_locations.get(oid, set())
            item = {"object_id": oid.binary(),
                    "holders_hint": sorted(merged) or None}
            if sizes and sizes.get(oid):
                item["nbytes"] = int(sizes[oid])
            items.append(item)
        if not items or self.raylet is None:
            return {}
        payload: dict = {"objects": items}
        if timeout_s is not None:
            payload["timeout_s"] = float(timeout_s)
        try:
            res = await self.raylet.call("pull_objects", payload)
        except Exception:
            log.debug("batched pull failed", exc_info=True)
            return {}
        for oid in hints:
            if (res or {}).get(oid.hex()):
                # the holder we now KNOW is our own node
                self._obj_locations[oid] = {self.node_id.binary()}
        return res or {}

    # ----------------------------------------------------------------- get
    async def get_async(self, refs: list[ObjectRef], timeout: float | None = None):
        refs = list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        if len(refs) <= 1:
            return [await self._get_one(ref, deadline) for ref in refs]
        # inline sweep: ready non-shm entries resolve without spawning a
        # task per ref (the common get([...]) over completed results)
        out: list = [None] * len(refs)
        pending: list[int] = []
        for i, ref in enumerate(refs):
            entry = self.memory_store.get(ref.id)
            if (entry is not None and entry.ready.is_set()
                    and entry.error is None and not entry.in_shm):
                if entry.packed is not None:
                    out[i] = serialization.unpack(entry.packed)
                else:
                    out[i] = entry.value
            else:
                pending.append(i)
        if not pending:
            return out
        # batched location priming: one kv_multi_get covers every shm ref
        # whose holder set is unknown, instead of one directory RPC per
        # ref inside the pulls below
        await self._prime_locations([refs[i] for i in pending])
        # batched pull: every ready shm ref that is not local yet rides
        # ONE pull_objects round trip (a cross-node KV-manifest set or
        # multi-arg fetch lands in one RTT); _get_one then reads the
        # local copies zero-copy, and misses keep their per-ref fallback
        if self.store is not None:
            need_pull = {}
            for i in pending:
                oid = refs[i].id
                entry = self.memory_store.get(oid)
                if (entry is not None and entry.ready.is_set()
                        and entry.in_shm and oid not in need_pull
                        and not self.store.contains(oid)):
                    need_pull[oid] = self._obj_locations.get(oid, set())
            if len(need_pull) >= 2:
                await self.pull_objects_batch(need_pull)
        results = await asyncio.gather(
            *(self._get_one(refs[i], deadline) for i in pending),
            return_exceptions=True)
        for i, r in zip(pending, results):
            if isinstance(r, BaseException):
                raise r  # first error in ref order, like the serial path
            out[i] = r
        return out

    async def _prime_locations(self, refs: list[ObjectRef]):
        """Coalesce location misses for ready shm-resident refs into ONE
        GCS kv_multi_get (ref: owner-resident metadata — the slow path
        paid one obj_loc kv_get per ref)."""
        need = []
        seen = set()
        for ref in refs:
            oid = ref.id
            if oid in seen or oid in self._obj_locations:
                continue
            entry = self.memory_store.get(oid)
            if (entry is not None and entry.ready.is_set() and entry.in_shm
                    and (self.store is None or not self.store.contains(oid))):
                seen.add(oid)
                need.append(oid)
        if len(need) < 2:
            return
        try:
            blobs = await self.gcs.call(
                "kv_multi_get", {"ns": "obj_loc",
                                 "keys": [o.hex() for o in need]})
        except Exception:
            return  # per-ref pulls fall back to the directory themselves
        for oid in need:
            blob = (blobs or {}).get(oid.hex())
            if blob:
                try:
                    self._obj_locations[oid] = set(pickle.loads(blob))
                except (pickle.UnpicklingError, TypeError, EOFError):
                    pass  # torn directory blob: treated as a cache miss

    async def _get_one(self, ref: ObjectRef, deadline: float | None):
        oid = ref.id
        pull_fails = 0
        while True:
            # timeout=0 is a non-blocking fetch: ready values are returned,
            # the timeout only fires where we would otherwise block
            # (ref: ray worker.get timeout semantics, worker.py:2757)
            remaining = None if deadline is None else deadline - time.monotonic()
            expired = remaining is not None and remaining <= 0
            entry = self.memory_store.get(oid)
            if entry is not None and entry.ready.is_set():
                if entry.error is not None:
                    raise entry.error
                if not entry.in_shm:
                    if entry.packed is not None:
                        return serialization.unpack(entry.packed)
                    return entry.value
                # owned shm result — may live on the executing node's store
                # (spillback): fall through to the shm/pull path below
            if self.store is None:
                # remote driver: no local arena. Owned memory-store entries
                # returned above; anything shm-resident (task results,
                # borrowed large objects) is materialized over the raylet
                # connection via the chunked transfer RPCs.
                if entry is not None and not entry.ready.is_set():
                    if expired:
                        raise GetTimeoutError(f"get timed out on {ref}")
                    await _wait_event(entry.ready, remaining)
                    continue
                if entry is not None or ref.owner_address is None or \
                        tuple(ref.owner_address) == self.address:
                    data = await self._fetch_via_raylet(oid)
                    if data is not None:
                        return serialization.unpack(data)
                    if expired:
                        raise GetTimeoutError(f"get timed out on {ref}")
                    pull_fails += 1
                    if pull_fails >= 5:
                        if await self._try_reconstruct(oid):
                            pull_fails = 0
                            continue
                        raise ObjectLostError(f"{ref}: no reachable copy")
                    await asyncio.sleep(0.05)
                    continue
                # borrowed: ask the owner (inline reply or shm indirection)
                if expired:
                    raise GetTimeoutError(f"get timed out on {ref}")
                try:
                    reply = await self._owner_call(
                        ref, "get_object", {"object_id": oid.binary()}, remaining
                    )
                except asyncio.TimeoutError:
                    raise GetTimeoutError(f"get timed out on {ref}") from None
                if reply.get("error") is not None:
                    raise reply["error"]
                if reply.get("inline") is not None:
                    return serialization.unpack(reply["inline"])
                data = await self._fetch_via_raylet(oid)
                if data is not None:
                    return serialization.unpack(data)
                if expired:
                    raise GetTimeoutError(f"get timed out on {ref}")
                pull_fails += 1
                if pull_fails >= 15:
                    raise ObjectLostError(f"{ref}: no reachable copy")
                await asyncio.sleep(0.05)
                continue
            if self.store.contains(oid):
                try:
                    # dedicated executor: the loop's default pool is shared
                    # with arbitrary user run_in_executor(None, ...) work —
                    # actor code commonly parks blocking api.get calls
                    # there, and once those occupy every default thread the
                    # store read that would unblock them queues behind them
                    # forever (executor self-deadlock at ~6 concurrent gets)
                    return await self.loop.run_in_executor(
                        self._store_executor(), self.store.get, oid, 10_000)
                except object_store.ObjectEvictedError:
                    # Local copy was LRU-evicted under memory pressure between
                    # contains() and get(): re-pull from another holder (the
                    # raylet consults the GCS directory); no holder → lost,
                    # unless lineage can re-execute the producing task.
                    ok = await self._pull_via_raylet(oid)
                    if expired:
                        raise GetTimeoutError(f"get timed out on {ref}") from None
                    if not ok:
                        if await self._try_reconstruct(oid):
                            continue
                        raise ObjectLostError(
                            f"{ref} was evicted and no other copy exists"
                        ) from None
                    continue
            if entry is not None:
                if entry.ready.is_set():  # owned, in_shm, not local: pull it
                    ok = await self._pull_via_raylet(oid)
                    if expired:
                        # pull issued (or refused) but the value is still not
                        # local and the deadline passed: raise rather than
                        # spinning pull RPCs forever on a stalled transfer
                        raise GetTimeoutError(f"get timed out on {ref}")
                    if not ok:
                        pull_fails = pull_fails + 1
                        # distinguish "not there yet" from "gone": a local
                        # eviction tombstone or repeated no-holder pulls
                        # mean the object is lost -> lineage re-execution
                        if self.store.is_evicted(oid) or pull_fails >= 5:
                            if await self._try_reconstruct(oid):
                                pull_fails = 0
                                continue
                            raise ObjectLostError(
                                f"{ref} was evicted and no other copy exists"
                            )
                        await asyncio.sleep(0.05)
                    continue
                # owned, pending task result
                if expired:
                    raise GetTimeoutError(f"get timed out on {ref}")
                await _wait_event(entry.ready, remaining)
                continue
            # borrowed ref: ask the owner
            if expired:
                raise GetTimeoutError(f"get timed out on {ref}")
            if ref.owner_address is None or tuple(ref.owner_address) == self.address:
                await asyncio.sleep(0.01)
                continue
            try:
                reply = await self._owner_call(
                    ref, "get_object", {"object_id": oid.binary()}, remaining
                )
            except asyncio.TimeoutError:
                raise GetTimeoutError(f"get timed out on {ref}") from None
            if reply.get("error") is not None:
                raise reply["error"]
            if reply.get("inline") is not None:
                return serialization.unpack(reply["inline"])
            # large object: pull into local shm through our raylet
            ok = await self._pull_via_raylet(oid)
            if not ok:
                pull_fails += 1
                if pull_fails in (5, 15, 30):  # escalate: owner re-executes
                    try:
                        await self._owner_call(
                            ref, "recover_object", {"object_id": oid.binary()}, 10
                        )
                    except Exception:
                        log.debug("recover_object escalation failed",
                                  exc_info=True)
                if pull_fails >= 45:
                    # the owner keeps claiming shm residency but no holder
                    # can produce the bytes and recovery changed nothing —
                    # without a deadline this loop would spin forever on a
                    # stale owner entry; surface the loss instead
                    raise ObjectLostError(f"{ref}: no reachable copy")
                await asyncio.sleep(0.05)
                continue

    async def _fetch_via_raylet(self, oid: ObjectID) -> bytes | None:
        """Client mode: materialize a shm-resident object through the raylet
        connection (pull to the raylet's arena if needed, then stream it
        with the chunked transfer RPCs — the remote-driver read path)."""
        obj = {"object_id": oid.binary()}
        try:
            ok = await self._pull_via_raylet(oid)
            if not ok:
                return None
            meta = await self.raylet.call("fetch_object_meta", obj)
            if meta is None:
                return None
            size = meta["size"]
            chunk = self.cfg.object_transfer_chunk_size
            offsets = list(range(0, size, chunk))
            parts: list = [None] * len(offsets)
            window = asyncio.Semaphore(4)  # pipeline: hide per-chunk RTT

            async def fetch(i: int, off: int):
                async with window:
                    data = await self.raylet.call(
                        "fetch_object_chunk",
                        {"object_id": oid.binary(), "offset": off,
                         "length": min(chunk, size - off)},
                    )
                    if data is None:  # holder lost mid-stream: abort the rest
                        raise LookupError("chunk gone")
                    parts[i] = data

            tasks = [asyncio.ensure_future(fetch(i, off))
                     for i, off in enumerate(offsets)]
            try:
                await asyncio.gather(*tasks)
            except LookupError:
                # gather doesn't cancel siblings: stop the queued fetches so
                # a multi-GB failure doesn't keep streaming dead chunks
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                return None
            finally:
                try:
                    await self.raylet.call("fetch_object_done", obj)
                except (rpc.RpcError, OSError):
                    pass  # raylet gone: the pin dies with it
            return b"".join(parts)
        except rpc.ConnectionLost:
            return None

    async def _owner_call(self, ref: ObjectRef, method: str, payload: dict,
                          timeout: float | None):
        conn = await rpc.connect(*ref.owner_address, timeout=self.cfg.rpc_connect_timeout_s)
        try:
            return await conn.call(method, payload, timeout=timeout)
        finally:
            await conn.close()

    # ---------------------------------------------------------------- wait
    async def wait_async(self, refs, num_returns, timeout, fetch_local=True):
        """Event-driven wait: owned refs await their memory-store event,
        borrowed refs park one long 'wait_object' call at the owner
        (owner-push readiness) — no per-tick probe RPCs (ref: ray.wait
        via WaitManager, memory-store wakeups)."""
        refs = list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout

        # fast path: resolve already-ready refs synchronously — the common
        # wait() call sees mostly-complete refs and must not pay a watcher
        # task per ref
        ready_idx_fast: set[int] = set()
        for i, ref in enumerate(refs):
            if len(ready_idx_fast) >= num_returns:
                break
            entry = self.memory_store.get(ref.id)
            if entry is not None and entry.ready.is_set():
                ready_idx_fast.add(i)
            elif entry is None and self.store is not None \
                    and self.store.contains(ref.id):
                ready_idx_fast.add(i)
        if len(ready_idx_fast) >= num_returns:
            ready = [r for i, r in enumerate(refs) if i in ready_idx_fast]
            pending = [r for i, r in enumerate(refs) if i not in ready_idx_fast]
            return ready, pending

        async def one_ready(ref) -> bool:
            entry = self.memory_store.get(ref.id)
            if entry is not None:
                await entry.ready.wait()
                return True
            if self.store is not None and self.store.contains(ref.id):
                return True
            if not ref.owner_address or tuple(ref.owner_address) == self.address:
                # unknown local object: appears when its entry is created
                while self.store is None or not self.store.contains(ref.id):
                    entry = self.memory_store.get(ref.id)
                    if entry is not None:
                        await entry.ready.wait()
                        return True
                    await asyncio.sleep(0.05)
                return True
            park_fails = 0
            while True:  # borrowed: park at the owner
                try:
                    r = await self._owner_call(
                        ref, "wait_object",
                        {"object_id": ref.id.binary(), "timeout": 30.0}, 40.0,
                    )
                    park_fails = 0
                except Exception:
                    # capped exponential backoff: an owner mid-restart gets
                    # room to come back instead of a fixed-rate hammer
                    park_fails += 1
                    await asyncio.sleep(min(2.0, 0.25 * (2 ** park_fails))
                                        * (0.5 + random.random()))
                    continue
                if r.get("ready"):
                    if fetch_local and r.get("error") is None:
                        # start moving the payload to this node (ref:
                        # ray.wait fetch_local semantics)
                        self._bg.spawn(
                            self._pull_via_raylet(ref.id), self.loop)
                    return True
                if not r.get("known"):
                    await asyncio.sleep(0.2)  # not created yet (or freed)

        tasks = {
            asyncio.ensure_future(one_ready(ref)): i for i, ref in enumerate(refs)
        }
        ready_idx: set[int] = set()
        try:
            while len(ready_idx) < num_returns and tasks:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                done, _ = await asyncio.wait(
                    tasks, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    break  # timed out
                for t in done:
                    idx = tasks.pop(t)
                    if (len(ready_idx) < num_returns and not t.cancelled()
                            and t.exception() is None and t.result()):
                        ready_idx.add(idx)  # extras stay pending (wait contract)
        finally:
            for t in tasks:
                t.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
        ready = [r for i, r in enumerate(refs) if i in ready_idx]
        pending = [r for i, r in enumerate(refs) if i not in ready_idx]
        return ready, pending

    # -------------------------------------------- owner-side object service
    async def rpc_get_object(self, conn, p):
        oid = ObjectID(p["object_id"])
        entry = self.memory_store.get(oid)
        if entry is None:
            if self.store is not None and self.store.contains(oid):
                return {"shm": True}
            return {"error": TaskError(f"object {oid} unknown to owner (freed?)")}
        await entry.ready.wait()
        if entry.error is not None:
            return {"error": entry.error}
        if entry.in_shm:
            return {"shm": True}
        if entry.packed is not None:
            return {"inline": entry.packed}
        meta, buffers = serialization.dumps_with_buffers(entry.value)
        return {"inline": _pack_bytes(meta, buffers, serialization.total_size(meta, buffers))}

    async def _try_reconstruct(self, oid: ObjectID) -> bool:
        """Re-execute the producing task to regenerate a lost object
        (ref: object_recovery_manager.h:43 — lineage-based recovery;
        deterministic task assumption, bounded attempts)."""
        task_id = oid.task_id()
        if task_id in self._cancelled_tasks:
            return False
        stash = self._lineage.get(task_id)
        if stash is None:
            return False
        n = self._reconstructions.get(oid, 0)
        if n >= 3:
            return False
        self._reconstructions[oid] = n + 1
        num_returns = stash["num_returns"]
        for i in range(num_returns):
            roid = ObjectID.for_task_return(task_id, i)
            self.memory_store[roid] = _MemEntry()  # fresh pending entries
        self.task_events.emit(task_id=task_id.hex(), name=stash.get("name", "task"),
                              state="PENDING_ARGS_AVAIL", reconstruction=n + 1)
        fresh = {**stash, "max_retries": self.cfg.default_max_task_retries}
        await self._submit_async(fresh)
        return True

    async def rpc_recover_object(self, conn, p):
        """Borrower-requested recovery of a lost owned object."""
        return await self._try_reconstruct(ObjectID(p["object_id"]))

    async def rpc_probe_object(self, conn, p):
        oid = ObjectID(p["object_id"])
        entry = self.memory_store.get(oid)
        if entry is not None:
            return entry.ready.is_set()
        return self.store is not None and self.store.contains(oid)

    async def rpc_wait_object(self, conn, p):
        """Owner-push readiness: the call parks here until the object is
        ready (or a timeout passes), replacing borrower-side probe polling
        (ref: WaitManager + owner memory-store wakeups)."""
        oid = ObjectID(p["object_id"])
        timeout = p.get("timeout", 60.0)
        entry = self.memory_store.get(oid)
        if entry is None:
            if self.store is not None and self.store.contains(oid):
                return {"ready": True}
            return {"ready": False, "known": False}
        deadline = time.monotonic() + timeout
        while not entry.ready.is_set():
            if conn._closed:  # requester gone: don't park for the full timeout
                return {"ready": False, "known": True}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"ready": False, "known": True}
            try:
                await asyncio.wait_for(entry.ready.wait(), min(1.0, remaining))
            except asyncio.TimeoutError:
                continue
        if entry.error is not None:
            return {"ready": True, "error": entry.error}
        return {"ready": True}

    # ------------------------------------------- native fast path (shm rings)
    # The steady-state submit->execute->reply loop of the reference's C++
    # NormalTaskSubmitter (normal_task_submitter.cc:28, core_worker.cc:2500)
    # realized over native SPSC shm rings: see core/fastpath.py for the
    # design. Everything here degrades to the ordinary RPC path.

    async def _fast_attach(self, key, state, w: _LeasedWorker):
        """Create a ring pair and hand it to a freshly leased same-node
        worker. Failure is silent: the lane simply never exists."""
        from ray_tpu.core import fastpath

        self._fast_ring_seq += 1
        # once per worker LEASE (lane attach), not per record; the pid
        # must be read live for fork-safe shm naming (a cached pid
        # would collide post-fork)
        name = f"rt_fp_{os.getpid()}_{self._fast_ring_seq}"  # raylint: disable=RT021 -- per-lease
        try:
            ring = fastpath.RingPair.create(name, self.cfg.fastpath_ring_bytes)
        except Exception:
            return
        try:
            ok = await w.conn.call(
                "attach_fast_ring",
                {"name": name, "owner": list(self.address)}, timeout=10)
        except Exception:
            ok = False
        if not ok or w not in state.workers:
            ring.close_pair()
            return
        lane = fastpath.FastLane(ring, w, key)
        t = _threading.Thread(target=self._fast_reader, args=(lane,),
                              name="rt-fastread", daemon=True)
        lane.reader = t
        w.fast_lane = lane
        self._fast_lanes.append(lane)
        t.start()

    def _try_fast_submit(self, fn, args, kwargs, resources,
                         max_retries=None):
        """User-thread fast submit. Returns an ObjectRef, or None to take
        the RPC path. Must never raise."""
        func_id = getattr(fn, "__rt_func_id__", None)
        if (func_id is None
                or not getattr(fn, "__rt_fast_ok__", False)
                or func_id not in self._registered_funcs):
            return None
        key = (func_id, tuple(sorted(resources.items())), None, -1, None,
               None)
        return self._fast_submit_keyed(fn, func_id, key, resources,
                                       args, kwargs,
                                       max_retries=max_retries)

    def _fast_submit_keyed(self, fn, func_id, key, resources, args, kwargs,
                           max_retries=None):
        """Shared fast-submit tail: the template path enters here directly
        with its precomputed scheduling key (skipping the per-call getattr
        probes and resources sort that _try_fast_submit re-derives)."""
        from ray_tpu.core import fastpath

        if func_id in self._fast_ineligible_funcs:
            return None
        for a in args:
            if isinstance(a, ObjectRef):
                return None  # top-level refs are value-resolved on the loop
        if kwargs:
            for a in kwargs.values():
                if isinstance(a, ObjectRef):
                    return None
        state = self.sched_keys.get(key)
        if state is None:
            return None
        lanes = [w.fast_lane for w in list(state.workers)
                 if w.fast_lane is not None and not w.fast_lane.broken]
        if not lanes:
            return None
        # Burst traffic (tasks in flight, or back-to-back submits) rides
        # any lane: the ring amortizes thread wakes over the pipeline.
        # The coalescing window (defer) is wider: even a slow-moving
        # burst (per-call cost inflated by neighbor load) should buffer —
        # deferral is safe because it additionally requires in-ring work
        # the worker is already chewing on (see _fast_register_and_push).
        # ns clock: the SAME read serves burst detection AND the flight
        # recorder's submit stamp (no float math, no second clock call)
        now_ns = time.perf_counter_ns()
        gap_ns = now_ns - self._fast_last_submit
        burst = gap_ns < 200_000
        self._fast_last_submit = now_ns
        lone = False
        if not burst and not any(ln.inflight for ln in lanes):
            # Completion fast lane: a lone submit-then-block call rides
            # the ring too — the blocking get() steals the reply-ring
            # consumer (fast_prepass), so the round trip is two futex
            # wakes instead of an RPC frame + event-loop hop on each
            # side. Only onto a worker with no RPC batch committed: if
            # every leased worker is mid-batch, the RPC path's
            # free-worker routing wins.
            lanes = [ln for ln in lanes if not ln.worker.busy]
            if not lanes:
                return None
            lone = True
        cap = self.cfg.fastpath_inflight_max
        n = len(lanes)
        # lone submit/get loops stick to one lane: its worker pump stays
        # hot (spin-paired, no futex sleep) and the blocking get's steal
        # loop stays single-lane; round-robin is for pipelined bursts
        start = 0 if lone else self._task_counter % n
        lane = None
        for i in range(n):
            cand = lanes[(start + i) % n]
            if len(cand.inflight) < cap:
                lane = cand
                break
        if lane is None:
            return None
        self._task_counter += 1
        task_id = TaskID.generate()
        tid = task_id.binary()
        # flight-recorder stamp: perf_counter_ns is the same
        # CLOCK_MONOTONIC the worker pops against, so pop - t0 IS the
        # submit-ring hop
        t0 = now_ns if self._rec_enabled else 0
        # wire-level tracing (2.1): one branch when off/unsampled, a
        # 25-byte leg + submit point span when sampled
        trace = (self._trace_submit_leg(
            task_id, getattr(fn, "__name__", "task"), "ring")
            if self._trace_on else b"")
        try:
            rec = fastpath.pack_task(tid, func_id, args, kwargs, t0, trace)
        except Exception:
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
            return None  # plain pickle can't carry it: cloudpickle path
        # cap also guards the pop buffer: a record the consumer can never
        # pop would wedge the ring (see rt_ring_pop_batch's kTooBig)
        if len(rec) > min(self.cfg.fastpath_record_max,
                          fastpath.POP_BUF_BYTES - 64):
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
            return None  # big args belong in the object store
        ref = self._fast_register_and_push(
            lane, task_id, rec,
            (fn, args, kwargs, resources, max_retries),
            defer=gap_ns < 2_000_000, t0=t0)
        if ref is None:
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
            return None
        lane.worker.idle_since = time.monotonic()  # keep the lease warm
        metrics.tasks_submitted.inc()
        # Demand signaling: tasks queued beyond one-per-worker must still
        # surface as lease demand (raylet _lease_waiters feeds the
        # autoscaler and spillback) even though they ride the rings — but
        # only once the backlog PERSISTS (see fast_backlog_since, kept in
        # seconds: _maybe_spawn_lease/_report_demand compare it against
        # time.monotonic(), the same clock as perf_counter on Linux).
        if len(lane.inflight) > 1:
            now_s = now_ns * 1e-9
            if state.fast_backlog_since == 0.0:
                state.fast_backlog_since = now_s
            elif (now_s - state.fast_backlog_since > 0.5
                    and now_s - self._fast_demand_kick > 0.25):
                self._fast_demand_kick = now_s
                self._call_on_loop(self._pump(key, state))
        else:
            state.fast_backlog_since = 0.0
        return ref

    def _fast_register_and_push(self, lane, task_id: TaskID, rec: bytes,
                                light, defer: bool = False, t0: int = 0,
                                track: bool = True):
        """Shared submit tail for task and actor lanes: register the
        in-flight entry under the cv, create the pending memory-store
        entry, then push — coalesced: the framed record lands in the
        lane's txbuf and rides one native batch push per burst instead of
        one ring lock + consumer wake per record. The record pushes
        immediately unless ``defer`` (burst detected) AND the worker
        already has in-ring work to chew on; a deferred tail is flushed
        by the threshold caps, the next blocking get() (fast_prepass), or
        the flusher thread's linger timer. On a closed ring undo — unless
        a concurrent break-lane already snapshotted our entry and
        resubmitted it over RPC, in which case the ref is handed out
        as-is (no duplicate call).

        ``track=False`` (the serve router's untracked calls): no
        memory-store entry and no ObjectRef — the return value is True
        on success, None for the RPC fallback; completion/break state
        reaches the caller through its registered loop waiter
        instead."""
        from ray_tpu.core import fastpath

        oid = ObjectID.for_task_return(task_id, 0)
        with self._fast_cv:
            if lane.broken or lane.retired:
                return None  # lost the race with a lane retire/break
            lane.inflight[task_id] = light
            # the oid entry carries the recorder's submit stamp too: one
            # dict op serves routing AND telemetry (t0 is 0 when the
            # recorder is off)
            self._fast_oid_lane[oid] = (lane, t0)
        if track:
            self.memory_store[oid] = _MemEntry()
        cfg = self.cfg
        kick = False
        undo = False
        framed = fastpath.frame_one(rec)
        maxrec = lane.flush_max_records or cfg.fastpath_flush_max_records
        maxbytes = lane.flush_max_bytes or cfg.fastpath_flush_max_bytes
        with lane.txlock:
            lane.txbuf.append(framed)
            lane.txbytes += len(framed)
            if (defer and maxrec > 1
                    and len(lane.inflight) > len(lane.txbuf)
                    and len(lane.txbuf) < maxrec
                    and lane.txbytes < maxbytes):
                status = 0
                kick = len(lane.txbuf) == 1  # arm the linger backstop
            else:
                status = self._fast_flush_locked(lane, timeout_ms=0)
                if (status == 0 and lane.txbuf
                        and lane.txbuf[-1] is framed):
                    # ring full and OUR record didn't make it in: keep the
                    # pre-coalescing spill semantics — undo and route this
                    # task over RPC (other workers stay usable) instead of
                    # parking it behind one saturated lane. Earlier
                    # deferred leftovers stay for the flusher.
                    lane.txbuf.pop()
                    lane.txbytes -= len(framed)
                    undo = True
                kick = bool(lane.txbuf)  # leftovers: flusher finishes
        if kick:
            self._fast_flush_kick()
        if status < 0 or undo:  # closed/unusable/full: undo, use RPC path
            if status < 0 and status != fastpath._ST_CLOSED:
                self._fast_break_lane(lane)  # kTooBig/sys: nobody else will
            with self._fast_cv:
                owned = lane.inflight.pop(task_id, None) is not None
                self._fast_oid_lane.pop(oid, None)
            if not owned:
                # a concurrent break-lane snapshotted the entry: tracked
                # tasks were resubmitted over RPC (the ref resolves);
                # untracked ones had their waiter woken with the broken
                # sentinel — either way the call is someone else's now
                return self._new_owned_ref(oid) if track else True
            if not track:
                return None
            self.memory_store.pop(oid, None)
            return None
        return self._new_owned_ref(oid) if track else True

    def _fast_flush_locked(self, lane, timeout_ms: int = 0) -> int:
        """Push the lane's buffered records (caller holds lane.txlock) in
        ONE native batch. Returns 0 when the buffer advanced or the
        remainder may stay buffered (ring momentarily full — the flusher
        retries); a negative ring status when the ring is closed/unusable
        (buffer dropped: every buffered task is registered in
        lane.inflight, and the break-lane path owns their recovery)."""
        from ray_tpu.core import fastpath

        if not lane.txbuf:
            return 0
        framed = (lane.txbuf[0] if len(lane.txbuf) == 1
                  else b"".join(lane.txbuf))
        pushed = lane.ring.push_batch(fastpath.SUB, framed, timeout_ms)
        if pushed < 0:
            lane.txbuf.clear()
            lane.txbytes = 0
            return pushed
        if pushed >= len(framed):
            self._fast_tx_flushes += 1
            self._fast_tx_records += len(lane.txbuf)
            rec_r = recorder.get_recorder() if self._rec_enabled else None
            if rec_r is not None:  # one event per FLUSH, not per task
                rec_r.record(b"", recorder.RING_PUSH,
                             a0=len(lane.txbuf), a1=pushed)
            lane.txbuf.clear()
            lane.txbytes = 0
            return 0
        if pushed:
            off = consumed = 0
            for fr in lane.txbuf:
                off += len(fr)
                if off > pushed:
                    break
                consumed += 1
            self._fast_tx_flushes += 1
            self._fast_tx_records += consumed
            rec_r = recorder.get_recorder() if self._rec_enabled else None
            if rec_r is not None:
                rec_r.record(b"", recorder.RING_PUSH,
                             a0=consumed, a1=pushed)
            del lane.txbuf[:consumed]
            lane.txbytes -= pushed
        return 0

    def _fast_flush_lane(self, lane, timeout_ms: int = 0) -> int:
        with lane.txlock:
            status = self._fast_flush_locked(lane, timeout_ms)
            leftover = bool(lane.txbuf)
        if status < 0:
            from ray_tpu.core import fastpath

            if status != fastpath._ST_CLOSED:
                self._fast_break_lane(lane)
        elif leftover:
            self._fast_flush_kick()  # ring full: the flusher retries
        return status

    def _fast_flush_kick(self):
        if self._fast_flusher_thread is None:
            self._ensure_fast_flusher()
        with self._fast_flush_cv:
            self._fast_flush_dirty = True
            self._fast_flush_cv.notify()

    def _ensure_fast_flusher(self):
        with self._fast_flush_cv:
            if self._fast_flusher_thread is not None:
                return
            t = _threading.Thread(target=self._fast_flusher,
                                  name="rt-fastflush", daemon=True)
            self._fast_flusher_thread = t
        t.start()

    def _fast_flusher(self):
        """Backstop flusher: bounds how long a burst's buffered tail can
        sit when no threshold or blocking get() flushes it (wait(), pure
        fire-and-forget). One wake per buffering episode, not per record."""
        linger = max(0.0, self.cfg.fastpath_flush_linger_us / 1e6)
        while not self._closed:
            with self._fast_flush_cv:
                while not self._fast_flush_dirty and not self._closed:
                    self._fast_flush_cv.wait(0.5)
                self._fast_flush_dirty = False
            if self._closed:
                return
            if linger:
                time.sleep(linger)  # let the burst tail accumulate
            again = False
            for lane in list(self._fast_lanes):
                if lane.txbytes and not lane.broken:
                    self._fast_flush_lane(lane, timeout_ms=20)
                    if lane.txbytes:
                        again = True
            if again:
                with self._fast_flush_cv:
                    self._fast_flush_dirty = True

    def fast_flush_stats(self) -> dict:
        """Coalescing counters for bench.py: batch pushes and the records
        they carried (avg_batch == 1.0 means no coalescing happened)."""
        flushes, records = self._fast_tx_flushes, self._fast_tx_records
        return {
            "flushes": flushes,
            "records": records,
            "avg_batch": (records / flushes) if flushes else 0.0,
        }

    def native_stats(self) -> dict:
        """Zero-copy view of the native transport counters: per-direction
        ring stats summed over live lanes (both sides of each ring share
        one shm stats block, so this covers the workers' halves too) and
        the local arena's store stats."""
        out: dict = {"ring": {}, "store": None}
        for which, label in ((0, "sub"), (1, "rep")):
            agg: dict[str, int] = {}
            for lane in list(self._fast_lanes):
                st = lane.ring.stats(which)
                if st:
                    for k, v in st.items():
                        if k == "peak_used":
                            # a SUM of per-lane peaks is an occupancy
                            # that never existed; the ring-sizing signal
                            # is the worst single lane
                            agg[k] = max(agg.get(k, 0), v)
                        else:
                            agg[k] = agg.get(k, 0) + v
            out["ring"][label] = agg
        if self.store is not None and not self.client_mode:
            try:
                out["store"] = self.store.stats()
            except object_store.ObjectStoreError:
                pass  # arena torn down mid-flush: skip this sample
        return out

    def _publish_recorder_metrics(self) -> None:
        """Flush-timer hook: fold the flight recorder's window and the
        native shm counters into the metrics registry (gauges + sampled
        stage histograms). Runs 1/s off the hot path; every aggregation
        here is bounded (capped windows, bulk bisect feed) so the flush
        can never grow past ~1ms and tax the A/B's CPU counter."""
        self._rec_enabled = recorder.enabled()  # refresh the hot-path gate
        # arena watermark gauges (tiering registry): live/peak/capacity
        # bytes per registered arena, sampled here so the rollup plane
        # gets watermark history on every flush. Bounded: one provider
        # call per arena, a handful of arenas per process.
        from ray_tpu.core import tiering as _tiering

        for aname, ast in _tiering.sample_arenas().items():
            metrics.arena_bytes.set(ast["bytes"], tags={"arena": aname})
            metrics.arena_peak_bytes.set(ast["peak"], tags={"arena": aname})
            if ast["capacity"]:
                metrics.arena_capacity_bytes.set(
                    ast["capacity"], tags={"arena": aname})
        # native ring/store gauges first, UNGATED: the shm counters move
        # with puts/gets/ring traffic even when no new task sample landed
        ns = self.native_stats()
        for label, agg in ns["ring"].items():
            for k, v in agg.items():
                metrics.fastpath_ring.set(v, tags={"which": label, "stat": k})
        if ns["store"]:
            for k, v in ns["store"].items():
                metrics.object_store_stat.set(v, tags={"stat": k})
        astats = self._actor_stats if self._rec_enabled else None
        if (astats is not None and astats.n
                and astats.n != self._actor_rec_published):
            # actor-call stage families, same bounded feed as tasks below
            # (stage tags prefixed actor_*)
            self._actor_rec_published = astats.n
            fresh = astats.new_since_flush()
            if fresh:
                for i, name in enumerate(recorder.LATENCY_STAGES):
                    metrics.task_stage_seconds.observe_many(
                        [s[i] / 1e9 for s in fresh],
                        tags={"stage": f"actor_{name}"})
            win = astats.window(512)
            for i, name in enumerate(recorder.LATENCY_STAGES):
                vals = sorted(s[i] for s in win)
                for q, qn in ((0.5, "p50"), (0.99, "p99")):
                    metrics.task_stage_us.set(
                        recorder.percentile(vals, q) / 1e3,
                        tags={"stage": f"actor_{name}", "q": qn})
        stats = recorder.get_stats() if self._rec_enabled else None
        if stats is None or stats.n == 0 or stats.n == self._rec_published:
            return  # recorder off / idle: stage aggregation has no new work
        # write the drained tasks' SAMPLE slots into the recorder ring
        # now (bounded to the newest 64 per flush): the hot path only
        # stored raw tuples, and timeline/event expansion reads these
        rec_r = recorder.get_recorder()
        prev = max(self._rec_published, 0)
        if rec_r is not None and stats.n > prev:
            for raw in stats.raw_window(min(stats.n - prev, 64)):
                ring_ns, deser_ns, exec_ns, reply_ns, total = \
                    recorder.decode_sample(raw)
                rec_r.record_sample(raw[2], raw[1], ring_ns, deser_ns,
                                    exec_ns, reply_ns, total)
        self._rec_published = stats.n
        metrics.recorder_samples.set(stats.n)
        # histogram feed is bounded per flush (newest samples win): under
        # full load this is deliberate sampling, not a per-task tax
        fresh = stats.new_since_flush()
        if fresh:
            for i, name in enumerate(recorder.LATENCY_STAGES):
                metrics.task_stage_seconds.observe_many(
                    [s[i] / 1e9 for s in fresh], tags={"stage": name})
        win = stats.window(512)
        for i, name in enumerate(recorder.LATENCY_STAGES):
            vals = sorted(s[i] for s in win)
            for q, qn in ((0.5, "p50"), (0.99, "p99")):
                metrics.task_stage_us.set(
                    recorder.percentile(vals, q) / 1e3,
                    tags={"stage": name, "q": qn})

    def _latency_snapshot(self) -> dict | None:
        """Publishable per-stage latency window (GCS ns="latency"):
        stage duration lists for list_task_latency percentiles plus the
        newest raw samples (wall-anchored) for timeline enrichment.
        Skipped while idle — the flush marks ``_lat_published`` after a
        successful kv_put, so an idle driver doesn't decode/pickle/ship
        a byte-identical ~40KB window every second forever."""
        stats = recorder.get_stats() if recorder.enabled() else None
        rec_r = recorder.get_recorder() if stats is not None else None
        if rec_r is None or stats.n == self._lat_published:
            return None
        snap = stats.snapshot(rec_r.anchor_wall, rec_r.anchor_perf)
        if snap is None:
            return None
        samples = []
        for raw in stats.raw_window(256):
            ring_ns, deser_ns, exec_ns, reply_ns, _total = \
                recorder.decode_sample(raw)
            samples.append((raw[2].hex(), rec_r.wall_ns(raw[1]), ring_ns,
                            deser_ns, exec_ns, reply_ns))
        snap["samples"] = samples
        snap["worker_id"] = self.worker_id.hex()
        return snap

    def _actor_latency_snapshot(self) -> dict | None:
        """Latency-source hook (flush timer): the actor-call stage window
        as actor_*-prefixed stage lists, skipped while idle. Publish is
        confirmed by _actor_latency_confirm only after the kv_put LANDS,
        so a transient GCS error republishes the window next flush."""
        stats = self._actor_stats
        if stats is None or stats.n == 0 or stats.n == self._actor_lat_published:
            return None
        win = stats.window(1024)
        if not win:
            return None
        self._actor_lat_pending = stats.n
        return {"count": stats.n,
                "stages": {f"actor_{name}": [s[i] for s in win]
                           for i, name in enumerate(recorder.LATENCY_STAGES)}}

    def _actor_latency_confirm(self) -> None:
        self._actor_lat_published = self._actor_lat_pending

    async def _fast_actor_attach(self, actor_id: ActorID, conn):
        """Ring lane to a same-node actor's worker: actor calls then skip
        the loop + socket entirely, with the ring's SPSC order AS the
        per-caller FIFO (ref: actor_task_submitter.h:75 ordered sends)."""
        from types import SimpleNamespace

        from ray_tpu.core import fastpath

        if self.cfg.tunnel_force:
            return  # bench/test: the tunnel lane owns even local actors
        existing = self._fast_actor_lanes.get(actor_id)
        if existing is not None:
            if not existing.broken and existing.worker.conn is conn:
                return  # live lane on this very connection
            # stale lane from a previous (dead) connection: break it now
            # rather than waiting for the health sweep — otherwise the
            # reconnected actor would silently stay on the RPC path
            self._fast_break_lane(existing)
        info = self._actor_info.get(actor_id)
        if info is None or info.get("node_id") != self.node_id:
            return
        self._fast_ring_seq += 1
        name = f"rt_fp_{os.getpid()}_a{self._fast_ring_seq}"
        try:
            ring = fastpath.RingPair.create(name, self.cfg.fastpath_ring_bytes)
        except Exception:
            return
        try:
            ok = await conn.call(
                "attach_fast_ring",
                {"name": name, "kind": "actor",
                 "owner": list(self.address)}, timeout=10)
        except Exception:
            ok = False
        methods = None
        if isinstance(ok, dict):  # 1.8 reply: method eligibility table
            methods = ok.get("methods")
            ok = ok.get("ok")
        if not ok or self._actor_conns.get(actor_id) is not conn:
            ring.close_pair()
            return
        lane = fastpath.FastLane(
            ring,
            SimpleNamespace(conn=conn, fast_lane=None, idle_since=0.0,
                            queued=0),
            ("actor", actor_id))
        lane.methods = methods
        lane.drain_evt = asyncio.Event()  # created ON the loop (waiters too)
        t = _threading.Thread(target=self._fast_reader, args=(lane,),
                              name="rt-fastread-actor", daemon=True)
        lane.reader = t
        self._fast_actor_lanes[actor_id] = lane
        self._fast_lanes.append(lane)
        t.start()

    # ------------------------------------- cross-node tunnels (core/tunnel.py)
    def _tunnel_ok(self) -> bool:
        return (self.cfg.node_tunnel and self.cfg.fastpath_enabled
                and not self.client_mode and not self._closed)

    def _tunnel_client(self):
        if self._tunnels is None:
            from ray_tpu.core import tunnel as _tunnel

            self._tunnels = _tunnel.TunnelClient(self)
        return self._tunnels

    def tunnel_stats(self) -> dict:
        """Tunnel coalescing counters (bench.py tunnel arm, tests);
        zeros when no tunnel was ever dialed."""
        if self._tunnels is None:
            return {"tunnels": 0, "lanes": 0, "tx_frames": 0,
                    "tx_records": 0, "rx_frames": 0, "rx_records": 0,
                    "avg_batch": 0.0}
        return self._tunnels.stats()

    async def _tunnel_actor_attach(self, actor_id: ActorID, conn):
        """Tunnel lane to a REMOTE actor's worker (the cross-node twin
        of _fast_actor_attach): actor calls then ride coalesced
        ring-format frames over the node tunnel instead of per-call
        pickled RPC specs. Failure is silent — the RPC path serves the
        actor and the health loop retries the bind."""
        from types import SimpleNamespace

        from ray_tpu.core import fastpath

        existing = self._fast_actor_lanes.get(actor_id)
        if existing is not None:
            if not existing.broken:
                # live — or RETIRED but still draining: force-breaking a
                # draining lane would resubmit records the worker is
                # still executing (double execution); the drain path
                # closes it and pops the map entry, after which the
                # health sweep lands back here for a fresh bind
                return
            self._fast_break_lane(existing)  # idempotent map cleanup
        info = self._actor_info.get(actor_id)
        if info is None or info.get("state") != ALIVE:
            return
        same = info.get("node_id") == self.node_id
        if same and not self.cfg.tunnel_force:
            return  # same-node: the shm ring lane owns this actor
        if same:
            addr = tuple(self.raylet_address)
        else:
            nid = info.get("node_id")
            nid_hex = nid.hex() if hasattr(nid, "hex") else str(nid)
            addr = await self._node_address(nid_hex)
            if addr is None:
                return
        try:
            bound = await self._tunnel_client().bind_lane(
                tuple(addr), kind="actor", actor_id=actor_id.hex())
        except Exception:
            log.debug("tunnel actor bind failed", exc_info=True)
            return
        if bound is None:
            return
        tun, lane_id, ring, methods = bound
        if (self._actor_conns.get(actor_id) is not conn
                or self._fast_actor_lanes.get(actor_id) is not None):
            ring.close_pair()
            return
        lane = fastpath.FastLane(
            ring,
            SimpleNamespace(conn=conn, fast_lane=None, idle_since=0.0,
                            queued=0),
            ("actor", actor_id))
        lane.methods = methods
        lane.drain_evt = asyncio.Event()
        # widened coalescing: one tunnel frame amortizes over far more
        # records than one ring wake — let bursts pack deeper
        lane.flush_max_records = self.cfg.fastpath_flush_max_records * 8
        lane.flush_max_bytes = self.cfg.fastpath_flush_max_bytes * 8
        tun.register(lane_id, lane, ring)
        self._fast_actor_lanes[actor_id] = lane
        self._fast_lanes.append(lane)
        self._tunnel_actor_seen[actor_id] = tuple(addr)

    async def _tunnel_task_attach(self, key, state, w: _LeasedWorker):
        """Tunnel lane to a remotely leased task worker (the cross-node
        twin of _fast_attach): eligible submits then ride "Q"/"R"
        records over the node tunnel, coalesced by the same txbuf
        machinery the shm lanes use."""
        from ray_tpu.core import fastpath

        try:
            bound = await self._tunnel_client().bind_lane(
                tuple(w.raylet_address), kind="task",
                worker_id=w.worker_id)
        except Exception:
            log.debug("tunnel task bind failed", exc_info=True)
            return
        if bound is None:
            return
        tun, lane_id, ring, _ = bound
        if w not in state.workers or w.fast_lane is not None:
            ring.close_pair()
            return
        lane = fastpath.FastLane(ring, w, key)
        lane.flush_max_records = self.cfg.fastpath_flush_max_records * 8
        lane.flush_max_bytes = self.cfg.fastpath_flush_max_bytes * 8
        tun.register(lane_id, lane, ring)
        w.fast_lane = lane
        self._fast_lanes.append(lane)

    def _tunnel_shrink_args(self, args, kwargs):
        """Descriptor conversion for an oversized tunnel record: every
        big top-level value (bytes / buffer-backed array) seals into the
        LOCAL shm arena and its slot ships a (node, oid, nbytes)
        TunnelArgRef instead — the receiver adopts the set via one
        batched pull. Returns (args, kwargs, pin refs) or None when
        nothing here is shrinkable (the call takes the RPC path, which
        ships payloads through the object plane anyway)."""
        from ray_tpu.core import fastpath

        cap = self.cfg.tunnel_inline_max
        pins: list = []

        def conv(v):
            n = getattr(v, "nbytes", None)
            if n is None and isinstance(v, (bytes, bytearray, memoryview)):
                n = len(v)
            if not isinstance(n, int) or n <= cap:
                return v
            try:
                ref = self.put_value(v, prefer_shm=True)
            except Exception:
                return v
            pins.append(ref)
            return fastpath.TunnelArgRef(
                ref.id.binary(), tuple(self.address),
                self.node_id.binary(), int(n))

        args2 = tuple(conv(a) for a in args)
        kwargs2 = ({k: conv(v) for k, v in kwargs.items()}
                   if kwargs else kwargs)
        if not pins:
            return None
        return args2, kwargs2, pins

    def actor_call_template(self, actor_id: ActorID, method: str,
                            num_returns, concurrency_group) -> ActorCallTemplate:
        """Build the frozen per-(handle, method) submission template
        (cached on the ActorMethod by ref.ActorMethod.remote)."""
        t = ActorCallTemplate()
        t.core = self
        t.actor_id = actor_id
        t.method = method
        t.mkey = b"am:" + method.encode()
        t.opts_ok = num_returns == 1 and concurrency_group is None
        t.lane = None
        return t

    def fast_actor_lane_stats(self, actor_id: ActorID) -> dict | None:
        """Seq/out-of-order accounting of an actor's ring lane (tests,
        bench): None when no lane is attached."""
        lane = self._fast_actor_lanes.get(actor_id)
        if lane is None:
            return None
        return {"next_seq": lane.next_seq, "done_seq": lane.done_seq,
                "ooo_replies": lane.ooo_replies, "broken": lane.broken,
                "retired": lane.retired, "inflight": len(lane.inflight)}

    def _fast_resolve_ref_args(self, args, kwargs):
        """Top-level ObjectRef arguments: resolve the locally-ready ones
        inline on the caller thread (the completion lane's
        get_local_prepass — ready memory-store entries and sealed local
        shm objects, zero event-loop round trip) so the call stays on the
        ring. Returns (args, kwargs, ok); ok=False when any ref is still
        pending/remote/errored — THAT call takes the RPC path (which owns
        dependency blocking and error surfacing), the lane stays live."""
        refs = [a for a in args if isinstance(a, ObjectRef)]
        if kwargs:
            refs.extend(v for v in kwargs.values()
                        if isinstance(v, ObjectRef))
        if not refs:
            return args, kwargs, True
        hits = self.get_local_prepass(refs)
        for r in refs:
            hit = hits.get(r.id)
            if hit is None or hit[0] != "V":
                return args, kwargs, False
        args = tuple(hits[a.id][1] if isinstance(a, ObjectRef) else a
                     for a in args)
        if kwargs:
            kwargs = {k: hits[v.id][1] if isinstance(v, ObjectRef) else v
                      for k, v in kwargs.items()}
        return args, kwargs, True

    def _try_fast_actor_submit(self, actor_id: ActorID, method: str,
                               args, kwargs, tmpl=None):
        """User-thread fast actor call; None -> RPC path for THIS call
        only (per-call downgrade — the lane survives). FIFO across the
        mixed stream: a slow-path call drains the lane's in-flight
        records before dispatching (_prepare_actor_task), and while RPC
        calls are queued/in-flight this gate keeps new calls off the ring
        so ring and socket traffic can never reorder a caller's calls."""
        from ray_tpu.core import fastpath

        # Loop-resident callers (the serve router, async actor methods
        # making nested calls) stay on the RPC path: its reply applies
        # directly ON the loop, while a ring completion detours through
        # the sweeper thread + migrate queue — two extra handoffs that
        # measured a ~40% serve_qps hit on a 2-vCPU box. The ring wins
        # for user threads, where the blocking get() steals the reply
        # consumer; a loop caller can never block-steal.
        if _threading.get_ident() == getattr(self.loop, "_thread_id", None):
            return None
        lane = tmpl.lane if tmpl is not None else None
        if lane is None or lane.broken or lane.retired:
            lane = self._fast_actor_lanes.get(actor_id)
            if lane is None or lane.broken or lane.retired:
                if tmpl is not None:
                    tmpl.lane = None
                return None
            if tmpl is not None:
                tmpl.lane = lane  # rebind on (re)attach
        # worker-shipped eligibility: generator methods and names the
        # worker never heard of go RPC per call, without a ring round trip
        mt = lane.methods
        if mt is not None:
            v = mt.get(method)
            if v is None or v[0] == "gen":
                return None
        # per-caller FIFO: never overtake queued/in-flight RPC calls
        if self._actor_queues.get(actor_id) or self._actor_inflight.get(
                actor_id):
            return None
        has_ref = any(isinstance(a, ObjectRef) for a in args)
        if not has_ref and kwargs:
            has_ref = any(isinstance(v, ObjectRef) for v in kwargs.values())
        if has_ref:
            args, kwargs, ok = self._fast_resolve_ref_args(args, kwargs)
            if not ok:
                return None  # pending/remote ref: RPC path for this call
        task_id = TaskID.generate_actor()
        tid = task_id.binary()
        now_ns = time.perf_counter_ns()
        t0 = now_ns if self._rec_enabled else 0
        mkey = tmpl.mkey if tmpl is not None else b"am:" + method.encode()
        # seq label rides the record (protocol 1.8): lock-free draw — a
        # racing retire is caught by _fast_register_and_push under the cv
        seq = next(lane.seq_counter)
        lane.next_seq = seq + 1  # advisory mirror (stats/tests)
        light = ("actor", actor_id, method, args, kwargs)
        pins = None
        tunnel = getattr(lane.ring, "tunnel", False)
        trace = (self._trace_submit_leg(
            task_id, method, "tunnel" if tunnel else "ring")
            if self._trace_on else b"")
        try:
            rec = fastpath.pack_actor_task(tid, mkey, args, kwargs, t0,
                                           seq, trace)
        except Exception:
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
            return None  # unpicklable args: RPC path for this call
        if len(rec) > self.cfg.tunnel_inline_max and tunnel:
            # oversized args do NOT ride the tunnel: seal them locally
            # and ship (node, oid, nbytes) descriptors; the worker
            # adopts the set via one batched pull. light keeps the
            # ORIGINAL args so break-lane recovery replays faithfully.
            shrunk = self._tunnel_shrink_args(args, kwargs)
            if shrunk is not None:
                s_args, s_kwargs, pins = shrunk
                try:
                    rec = fastpath.pack_actor_task(
                        tid, mkey, s_args, s_kwargs, t0, seq, trace)
                except Exception:
                    self._trace_pending.pop(
                        ObjectID.for_task_return(task_id, 0), None)
                    return None
        if len(rec) > min(self.cfg.fastpath_record_max,
                          fastpath.POP_BUF_BYTES - 64):
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
            return None  # big args belong in the object store
        gap_ns = now_ns - self._fast_last_submit
        self._fast_last_submit = now_ns
        if pins:
            self._tunnel_pins[task_id] = pins
        ref = self._fast_register_and_push(
            lane, task_id, rec, light,
            defer=gap_ns < 2_000_000, t0=t0)
        if ref is None:
            self._tunnel_pins.pop(task_id, None)
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
        else:
            metrics.actor_calls.inc()
        return ref

    def fast_actor_submit_loop(self, actor_id: ActorID, method: str,
                               args, kwargs, tmpl=None):
        """LOOP-thread fast actor submit — the serve data plane's router
        hop. The thread-path fast lane (_try_fast_actor_submit) refuses
        loop-resident callers because its reply detours through the
        migrate queue's 2ms linger; this variant registers an
        asyncio.Future the reply thread resolves DIRECTLY (one
        call_soon_threadsafe per reply batch), so a router coroutine
        gets (status, payload) the moment the completion record pops.

        UNTRACKED, by design: no ObjectRef, no memory-store entry, no
        task events, no migrate-queue bookkeeping, and — unlike every
        other fast path — no automatic RPC resubmission on a broken
        lane. The serve router OWNS the request lifecycle: its promise
        ref is the caller-visible handle, and its retry_on idempotency
        gate decides whether a maybe-executed request may replay (core
        at-least-once resubmission would re-execute non-idempotent
        requests behind the router's back). A lane break therefore
        surfaces as ConnectionLost from :meth:`fast_actor_await` — the
        same exception the RPC plane raises for a died-mid-request
        replica. Inline results skip the whole owned-object plane; only
        shm-sealed results (> fastpath_inline_result_max) mint a ref at
        await time to ride the normal read/free path.

        Unordered, also by design (every serve request is an
        independent logical call): no FIFO gate against queued RPC
        traffic in either direction.

        Returns ``(task_id, future)`` or None — None means THIS call
        takes the RPC path (per-call fallback, the lane stays live): no
        live lane, ineligible method, pending/remote ref args, or an
        oversized record. Sampled trace context rides the record's wire
        leg (2.1), so these calls are no longer trace-invisible. Decode
        the future with :meth:`fast_actor_await`."""
        from ray_tpu.core import fastpath

        lane = tmpl.lane if tmpl is not None else None
        if lane is None or lane.broken or lane.retired:
            lane = self._fast_actor_lanes.get(actor_id)
            if lane is None or lane.broken or lane.retired:
                if tmpl is not None:
                    tmpl.lane = None
                return None
            if tmpl is not None:
                tmpl.lane = lane  # rebind on (re)attach
        mt = lane.methods
        if mt is not None:
            v = mt.get(method)
            if v is None or v[0] == "gen":
                return None
        has_ref = any(isinstance(a, ObjectRef) for a in args)
        if not has_ref and kwargs:
            has_ref = any(isinstance(v, ObjectRef) for v in kwargs.values())
        if has_ref:
            args, kwargs, ok = self._fast_resolve_ref_args(args, kwargs)
            if not ok:
                return None  # pending/remote ref: RPC path for this call
        task_id = TaskID.generate_actor()
        tid = task_id.binary()
        now_ns = time.perf_counter_ns()
        t0 = now_ns if self._rec_enabled else 0
        mkey = tmpl.mkey if tmpl is not None else b"am:" + method.encode()
        seq = next(lane.seq_counter)
        lane.next_seq = seq + 1
        pins = None
        tunnel = getattr(lane.ring, "tunnel", False)
        trace = (self._trace_submit_leg(
            task_id, method, "tunnel" if tunnel else "ring")
            if self._trace_on else b"")
        try:
            rec = fastpath.pack_actor_task(tid, mkey, args, kwargs, t0,
                                           seq, trace)
        except Exception:
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
            return None  # unpicklable args: RPC path for this call
        if len(rec) > self.cfg.tunnel_inline_max and tunnel:
            # cross-node serve payload above the inline cap: descriptor
            # shipping (see _try_fast_actor_submit)
            shrunk = self._tunnel_shrink_args(args, kwargs)
            if shrunk is not None:
                s_args, s_kwargs, pins = shrunk
                try:
                    rec = fastpath.pack_actor_task(
                        tid, mkey, s_args, s_kwargs, t0, seq, trace)
                except Exception:
                    self._trace_pending.pop(
                        ObjectID.for_task_return(task_id, 0), None)
                    return None
        if len(rec) > min(self.cfg.fastpath_record_max,
                          fastpath.POP_BUF_BYTES - 64):
            self._trace_pending.pop(ObjectID.for_task_return(task_id, 0),
                                    None)
            return None  # big args belong in the object store
        if pins:
            self._tunnel_pins[task_id] = pins
        oid = ObjectID.for_task_return(task_id, 0)
        fut = self.loop.create_future()
        with self._fast_cv:
            self._fast_loop_waiters[oid] = fut
        self._fast_last_submit = now_ns
        # never defer: the caller's coroutine parks on the reply — a
        # buffered submit tail would trade its latency for nothing
        ok = self._fast_register_and_push(
            lane, task_id, rec, ("serve", actor_id, method),
            defer=False, t0=t0, track=False)
        if ok is None:
            with self._fast_cv:
                self._fast_loop_waiters.pop(oid, None)
            self._tunnel_pins.pop(task_id, None)
            self._trace_pending.pop(oid, None)
            return None
        metrics.actor_calls.inc()
        return task_id, fut

    async def fast_actor_await(self, task_id: TaskID, fut, timeout=None):
        """Decode a fast_actor_submit_loop reply: returns the call's
        value or raises its (typed) exception. Raises

        - :class:`FastLaneDeclined` when the worker NEED_SLOWed the
          record (stale method table) — the call never executed, the
          caller re-dispatches it over RPC;
        - ``rpc.ConnectionLost`` when the lane broke mid-flight — the
          replica may have executed the request, so the caller's own
          idempotency policy decides about a replay (exactly the
          died-mid-request contract of the RPC plane);
        - ``GetTimeoutError`` when ``timeout`` elapses first (the
          in-flight call keeps running; its late reply resolves the
          abandoned future, which nobody awaits)."""
        from ray_tpu.core import fastpath
        from ray_tpu.core.ref import GetTimeoutError

        deadline = None if timeout is None else time.monotonic() + timeout
        if timeout is None:
            status, payload = await fut
        else:
            # manual timer instead of asyncio.wait_for: this await is on
            # EVERY fast serve request, and wait_for's wrapper future +
            # timeout machinery measured real loop time at serve QPS
            timer = self.loop.call_later(timeout, _expire_future, fut)
            try:
                status, payload = await fut
            except asyncio.CancelledError:
                if getattr(fut, "_rt_expired", False):
                    raise GetTimeoutError(
                        "timed out waiting for fast-lane actor reply"
                    ) from None
                raise  # genuine cancellation (hedge loser): propagate
            finally:
                timer.cancel()
        if status == fastpath.OK:
            return serialization.unpack(payload)
        if status == fastpath.ERR:
            try:
                err = pickle.loads(payload)
            except Exception as e:
                err = TaskError(f"task failed: {e!r}")
            raise err
        if status == fastpath.OK_SHM:
            # large result sealed in the node arena: mint the ref NOW so
            # the read and the eventual free ride the normal owned-object
            # path (the reply processor created the entry + bookkeeping
            # for exactly this case)
            oid = ObjectID.for_task_return(task_id, 0)
            ref = self._new_owned_ref(oid)
            if self.store is not None:
                hit = self.store.try_get(oid)
                if hit is not None:
                    return hit[0]
            # REMAINING budget only: the future wait above already spent
            # part of the timeout, and re-spending it whole would let a
            # slow arena read overshoot the caller's deadline ~2x
            (value,) = await self.get_async(
                [ref], None if deadline is None
                else max(0.05, deadline - time.monotonic()))
            return value
        if status == fastpath.NEED_SLOW:
            raise FastLaneDeclined()
        raise rpc.ConnectionLost("fast lane broke mid-request")

    # -------------------------------------------------- streaming fast lane
    def fast_actor_submit_stream(self, actor_id: ActorID, method: str,
                                 args, kwargs, tmpl=None):
        """LOOP-thread fast STREAM submit (2.3): the generator analogue
        of :meth:`fast_actor_submit_loop`. The record goes out with a
        ``gm:`` method key, the worker drives the generator and flushes
        one "G" chunk record per yielded item (token deltas per fused
        decode block in the LLM case), and the stream's terminal is an
        ordinary reply on the lane's seq machinery. No per-item
        ObjectRef, memory-store entry, or task event — a chunk is two
        ring stores and one queue put end to end; only oversized items
        seal into the node arena and ride a CHUNK_SHM descriptor.

        Same untracked contract as the unary loop submit: no automatic
        replay on a broken lane (the serve router owns the request
        lifecycle), and RPC fallback is only valid while nothing has
        been consumed — a NEED_SLOW terminal means the worker declined
        before executing, so the per-item ObjectRef generator plane may
        re-dispatch safely.

        Returns ``(task_id, sink)`` for :meth:`fast_actor_stream`, or
        None — this call takes the per-item RPC generator path (no live
        lane, non-generator method, pending/remote ref args, oversized
        record)."""
        from ray_tpu.core import fastpath

        lane = tmpl.lane if tmpl is not None else None
        if lane is None or lane.broken or lane.retired:
            lane = self._fast_actor_lanes.get(actor_id)
            if lane is None or lane.broken or lane.retired:
                if tmpl is not None:
                    tmpl.lane = None
                return None
            if tmpl is not None:
                tmpl.lane = lane
        mt = lane.methods
        if mt is not None:
            v = mt.get(method)
            if v is None or v[0] != "gen":
                return None  # not a generator method on this worker
        has_ref = any(isinstance(a, ObjectRef) for a in args)
        if not has_ref and kwargs:
            has_ref = any(isinstance(v, ObjectRef) for v in kwargs.values())
        if has_ref:
            args, kwargs, ok = self._fast_resolve_ref_args(args, kwargs)
            if not ok:
                return None
        task_id = TaskID.generate_actor()
        tid = task_id.binary()
        now_ns = time.perf_counter_ns()
        t0 = now_ns if self._rec_enabled else 0
        mkey = b"gm:" + method.encode()
        seq = next(lane.seq_counter)
        lane.next_seq = seq + 1
        pins = None
        tunnel = getattr(lane.ring, "tunnel", False)
        trace = (self._trace_submit_leg(
            task_id, method, "tunnel" if tunnel else "ring")
            if self._trace_on else b"")
        oid = ObjectID.for_task_return(task_id, 0)
        try:
            rec = fastpath.pack_actor_task(tid, mkey, args, kwargs, t0,
                                           seq, trace)
        except Exception:
            self._trace_pending.pop(oid, None)
            return None  # unpicklable args: RPC generator path
        if len(rec) > self.cfg.tunnel_inline_max and tunnel:
            shrunk = self._tunnel_shrink_args(args, kwargs)
            if shrunk is not None:
                s_args, s_kwargs, pins = shrunk
                try:
                    rec = fastpath.pack_actor_task(
                        tid, mkey, s_args, s_kwargs, t0, seq, trace)
                except Exception:
                    self._trace_pending.pop(oid, None)
                    return None
        if len(rec) > min(self.cfg.fastpath_record_max,
                          fastpath.POP_BUF_BYTES - 64):
            self._trace_pending.pop(oid, None)
            return None
        if pins:
            self._tunnel_pins[task_id] = pins
        sink = _FastStreamSink(task_id, lane)
        with self._fast_cv:
            self._fast_stream_sinks[oid] = sink
        self._fast_last_submit = now_ns
        ok = self._fast_register_and_push(
            lane, task_id, rec, ("serve", actor_id, method),
            defer=False, t0=t0, track=False)
        if ok is None:
            with self._fast_cv:
                self._fast_stream_sinks.pop(oid, None)
            self._tunnel_pins.pop(task_id, None)
            self._trace_pending.pop(oid, None)
            return None
        metrics.actor_calls.inc()
        return task_id, sink

    async def fast_actor_stream(self, task_id: TaskID, sink, timeout=None):
        """Consume a fast-lane stream: async-iterates the call's yielded
        items in the worker's emit order. ``timeout`` bounds the WHOLE
        stream (first chunk through terminal), raising GetTimeoutError.
        A clean exhaustion returns after the terminal; a remote error
        raises the stream's typed exception; a NEED_SLOW terminal raises
        :class:`FastLaneDeclined` (nothing executed — safe to
        re-dispatch over the per-item RPC generator plane); a lane break
        raises ``rpc.ConnectionLost`` — chunks already consumed are
        never replayed. Early exit (``aclose`` / ``break`` /
        GeneratorExit) abandons the stream: the worker is told to stop
        pumping and late shm chunks free instead of leaking."""
        from ray_tpu.core import fastpath

        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while True:
                if deadline is None:
                    kind, status, payload, cseq = await sink.q.get()
                else:
                    try:
                        kind, status, payload, cseq = await asyncio.wait_for(
                            sink.q.get(),
                            max(0.0, deadline - time.monotonic()))
                    except asyncio.TimeoutError:
                        raise GetTimeoutError(
                            "timed out waiting for stream chunk") from None
                if kind == "chunk":
                    if status == fastpath.CHUNK:
                        yield serialization.unpack(payload)
                    else:  # CHUNK_SHM: sealed under return index seq+1
                        ref = self._fast_adopt_chunk_seal(
                            ObjectID.for_task_return(task_id, cseq + 1),
                            payload)
                        (value,) = await self.get_async(
                            [ref], None if deadline is None
                            else max(0.05, deadline - time.monotonic()))
                        yield value
                    continue
                if status == fastpath.OK:
                    return
                if status == fastpath.ERR:
                    try:
                        err = pickle.loads(payload)
                    except Exception as e:
                        err = TaskError(f"stream failed: {e!r}")
                    raise err
                if status == fastpath.NEED_SLOW:
                    raise FastLaneDeclined()
                raise rpc.ConnectionLost("fast lane broke mid-stream")
        finally:
            self.fast_stream_abandon(task_id, sink)

    def fast_stream_abandon(self, task_id: TaskID, sink) -> None:
        """Loop-side, idempotent stream teardown — runs on clean
        exhaustion AND on mid-stream disconnect. Unhooks the sink,
        tombstones a still-live stream so late chunks free their seals
        instead of leaking, frees everything queued-but-unconsumed, and
        best-effort tells a ring lane's worker to stop pumping
        (``stream_abandon`` RPC). Tunnel streams have no worker
        connection here — the serve layer cancels via
        ``cancel_request``, and a closed sink stops the pump on its
        next push anyway."""
        from ray_tpu.core import fastpath

        if sink.dead:
            return
        sink.dead = True
        oid = ObjectID.for_task_return(task_id, 0)
        live = False
        with self._fast_cv:
            if self._fast_stream_sinks.pop(oid, None) is not None:
                live = True
                self._fast_stream_dead[oid] = sink
                while len(self._fast_stream_dead) > 512:
                    self._fast_stream_dead.pop(
                        next(iter(self._fast_stream_dead)))
        # adopt-and-drop every unconsumed shm chunk (reorder buffer +
        # delivery queue) so the arena copies free now
        for cseq, (st, body) in list(sink.pending.items()):
            if st == fastpath.CHUNK_SHM:
                self._fast_adopt_chunk_seal(
                    ObjectID.for_task_return(task_id, cseq + 1), body)
        sink.pending.clear()
        sink.fin = None
        while not sink.q.empty():
            kind, st, body, cseq = sink.q.get_nowait()
            if kind == "chunk" and st == fastpath.CHUNK_SHM:
                self._fast_adopt_chunk_seal(
                    ObjectID.for_task_return(task_id, cseq + 1), body)
        if live:
            w = getattr(sink.lane, "worker", None)
            conn = getattr(w, "conn", None) if w is not None else None
            if conn is not None and not conn._closed:
                async def _notify():
                    try:
                        await conn.call("stream_abandon",
                                        {"task_ids": [task_id.binary()]})
                    except (rpc.ConnectionLost, OSError):
                        # best-effort: a dying worker's pump also stops
                        # on the closed ring / dead sink
                        pass
                self._bg.spawn(_notify(), self.loop)

    def _fast_adopt_chunk_seal(self, oid: ObjectID, payload: bytes):
        """Adopt a CHUNK_SHM seal into the owned-object plane at consume
        time: create the entry + location hint the migrate drain makes
        for an OK_SHM reply (chunks skip the migrate queue — no
        per-chunk task events by design) and mint the ref whose read and
        eventual drop ride the normal owned path. Dropping the returned
        ref immediately frees an orphaned seal."""
        from ray_tpu.core import fastpath

        ent = self.memory_store.get(oid)
        if ent is None:
            ent = _MemEntry()
            self.memory_store[oid] = ent
        if not ent.ready.is_set():
            ent.in_shm = True
            size, holder = fastpath.unpack_shm_desc(payload)
            holder = holder or self.node_id.binary()
            self._obj_locations.setdefault(oid, set()).add(holder)
            ent.ready.set()
        return self._new_owned_ref(oid)

    def _queue_loop_wakes(self, items) -> None:
        """Thread-safe: queue router-future resolutions and arm the loop
        drain at most once — while reply traffic flows the drain lingers
        armed (call_soon re-pass), so reply threads stop paying the
        self-pipe write per batch. From the loop itself the arm is a
        plain call_soon — call_soon_threadsafe writes the self-pipe even
        from the owning thread."""
        with self._fast_cv:
            self._fast_wake_q.extend(items)
            arm = not self._fast_wake_armed
            if arm:
                self._fast_wake_armed = True
        if arm:
            try:
                if _in_loop(self.loop):
                    self.loop.call_soon(self._drain_loop_wakes)
                else:
                    self.loop.call_soon_threadsafe(self._drain_loop_wakes)
            except RuntimeError:
                pass  # loop gone (shutdown)

    def _drain_loop_wakes(self):
        """Loop-side: resolve router futures with their raw reply
        tuples. A done future means the caller timed out and went away —
        its reply is dropped, except a shm-sealed result, whose entry is
        adopted-and-dropped so the arena copy frees instead of leaking
        (nobody else will ever mint its ref)."""
        from ray_tpu.core import fastpath

        with self._fast_cv:
            batch = self._fast_wake_q
            self._fast_wake_q = []
            if not batch:
                self._fast_wake_armed = False
                return
        for fut, status, payload, oid in batch:
            if type(fut) is _FastStreamSink:
                if not fut.dead:
                    fut.push(status, payload)
                elif status == fastpath.CHUNK_SHM:
                    # chunk for an abandoned stream: adopt-and-drop the
                    # orphaned seal so the arena copy frees
                    cseq, body = payload
                    self._fast_adopt_chunk_seal(
                        ObjectID.for_task_return(fut.task_id, cseq + 1),
                        body)
            elif not fut.done():
                fut.set_result((status, payload))
            elif status == fastpath.OK_SHM:
                self._new_owned_ref(oid)  # dropped at once: frees the seal
        # burst linger: stay armed one more tick while traffic flows
        self.loop.call_soon(self._drain_loop_wakes)

    def _fast_resubmit(self, task_id: TaskID, light, lost: bool = True) -> None:
        """Loop-side: re-route a fast-path call through the RPC path.
        ``lost=True`` (break-lane recovery: the worker died and may have
        executed the task) charges one retry from the user's budget and
        honors at-most-once — a max_retries=0 task FAILS rather than
        re-executing its side effects. ``lost=False`` (NEED_SLOW
        migration: the worker declined without executing) keeps the full
        budget."""
        tp = self._trace_pending.pop(
            ObjectID.for_task_return(task_id, 0), None)
        if tp is not None:
            # the fast leg never completed: materialize its submit span
            # now so the RPC replay's exec span has its parent, and keep
            # the call in the SAME trace (one logical call, one trace)
            self._trace_emit_submit_point(task_id, tp)
        if light[0] == "actor":
            _, actor_id, method, args, kwargs = light
            spec = {
                "task_id": task_id,
                "actor_id": actor_id,
                "method": method,
                "args": list(args),
                "kwargs": dict(kwargs),
                "num_returns": 1,
                "owner_address": self.address,
                "seq": None,
                "concurrency_group": None,
            }
            if tp is not None:  # sampled call: the RPC replay keeps the
                # trace (same parent submit span — one logical call)
                spec["trace_ctx"] = {"trace_id": tp[0],
                                     "parent_span_id": tp[2]}
            self._actor_queues.setdefault(actor_id, []).append(spec)
            self._bg.spawn(self._ensure_actor_pump(actor_id), self.loop)
        else:
            budget = light[4]
            if budget is None:
                budget = self.cfg.default_max_task_retries
            if lost:
                if budget <= 0:
                    # at-most-once: the user forbade re-execution and the
                    # worker may already have run the task's side effects
                    self._complete_task_error(
                        self._fast_light_to_spec(task_id, light, 0),
                        WorkerCrashedError())
                    return
                budget -= 1
            spec = self._fast_light_to_spec(task_id, light, budget)
            if tp is not None:
                spec["trace_ctx"] = {"trace_id": tp[0],
                                     "parent_span_id": tp[2]}
            self._bg.spawn(self._submit_async(spec), self.loop)

    def _fast_reader(self, lane):
        """Per-lane sweeper thread: drain the reply ring whenever no
        blocking get() has claimed consumption (fast_prepass steals the
        consumer role — one thread hop fewer per result — and the sweeper
        parks while that streak lasts)."""
        from ray_tpu.core import fastpath

        ring = lane.ring
        while not (self._closed or lane.broken):
            if time.monotonic() - lane.user_wants < 0.5:
                lane.resume_evt.wait(0.5)  # a get() streak owns the ring
                lane.resume_evt.clear()
                continue
            with lane.rx_lock:
                recs = ring.pop_batch(fastpath.REP, timeout_ms=200)
            if recs is None:
                break  # closed and drained
            if recs:
                self._fast_process_replies(lane, recs)
        self._fast_break_lane(lane)
        with lane.rx_lock:  # no stealing get() mid-pop
            ring.close_pair()  # the sweeper owns the unmap (single closer)

    def _fast_process_replies(self, lane, recs):
        """Record a batch of reply records (any thread): resolve blocking
        gets via the cv, queue loop-side bookkeeping. This is the
        DRIVER_APPLY point of the flight recorder: a stamped reply plus
        the submit-time t0 yields the full per-task stage sample (both
        ring hops, deserialize, exec) at the cost of one ring store and
        one recorder slot per task."""
        from ray_tpu.core import fastpath

        t_rx = time.perf_counter_ns()
        stats = recorder.get_stats() if self._rec_enabled else None
        # StageStats.add inlined below (ring/cap hoisted per batch): the
        # method-call frame alone is ~8% of the recorder's whole per-task
        # budget on slow interpreters (bench.py recorder_overhead_us)
        if stats is not None:
            sring, scap = stats.ring, stats.cap
        astats = self._actor_stats
        batch = []
        drained = False
        wake = None  # loop-waiter resolutions (serve fast-lane router)
        retire_serve = None  # lane whose method table went stale
        tspans = None  # sampled completions: wire-level call spans
        with self._fast_cv:
            for rec in recs:
                if rec[:1] == b"G":
                    # 2.3 stream chunk probe. A chunk never pops
                    # inflight / oid-lane / pins — the stream's terminal
                    # (an ordinary reply on the lane's seq machinery)
                    # owns all of that. Routing demands a full 16-byte
                    # task-id match against a registered sink, so a
                    # genuine reply whose tid happens to start with
                    # 0x47 ('G') falls through to the reply parse.
                    g = fastpath.unpack_chunk(rec)
                    if g is not None:
                        coid = ObjectID.for_task_return(TaskID(g[0]), 0)
                        sink = (self._fast_stream_sinks.get(coid)
                                or self._fast_stream_dead.get(coid))
                        if sink is not None:
                            if wake is None:
                                wake = []
                            # payload slot = (chunk_seq, body); the sink
                            # reorders on the loop side
                            wake.append((sink, g[1], (g[3], g[2]), coid))
                            continue
                    try:
                        tid_b, status, payload, stamp, seq, trc = \
                            fastpath.unpack_reply(rec)
                    except Exception:
                        # an ownerless chunk (late duplicate after the
                        # terminal cleared the stream) that does not
                        # parse as a reply: drop it, never kill the
                        # whole batch
                        continue
                else:
                    tid_b, status, payload, stamp, seq, trc = \
                        fastpath.unpack_reply(rec)
                task_id = TaskID(tid_b)
                light = lane.inflight.pop(task_id, None)
                if self._tunnel_pins:
                    # descriptor pins (oversized tunnel args): the reply
                    # landed, the receiver's pull is over — release the
                    # sealed copies
                    self._tunnel_pins.pop(task_id, None)
                oid = ObjectID.for_task_return(task_id, 0)
                ent = self._fast_oid_lane.pop(oid, None)
                if self._trace_pending and (
                        trc or (status == fastpath.NEED_SLOW
                                and light is not None
                                and light[0] == "serve")):
                    # sampled call: stamp the wire-level call span after
                    # the cv drops (span emit is just a dict append, but
                    # the cv guards hotter state than telemetry deserves).
                    # Serve NEED_SLOWs pop too — their RPC re-dispatch
                    # mints a fresh submit span, so the pending entry is
                    # dead (tracked NEED_SLOWs keep theirs for
                    # _fast_resubmit's trace_ctx handoff).
                    tp = self._trace_pending.pop(oid, None)
                    if (tp is not None and trc
                            and status != fastpath.NEED_SLOW):
                        if tspans is None:
                            tspans = []
                        tspans.append((oid, stamp, tp))
                if self._fast_loop_waiters:
                    fut = self._fast_loop_waiters.pop(oid, None)
                    if fut is not None:
                        if wake is None:
                            wake = []
                        wake.append((fut, status, payload, oid))
                if self._fast_stream_sinks or self._fast_stream_dead:
                    # stream terminal: deliver fin to a live sink (held
                    # there until the chunk tail drains); an abandoned
                    # stream's tombstone clears for good — nothing after
                    # the terminal will ever reference its seals
                    sink = self._fast_stream_sinks.pop(oid, None)
                    if sink is not None:
                        if wake is None:
                            wake = []
                        wake.append((sink, status, payload, oid))
                    else:
                        self._fast_stream_dead.pop(oid, None)
                if seq is not None and light is not None:
                    # out-of-order completion accounting (async actors
                    # reply as each method finishes): seq below the high
                    # water is evidence the lane completed out of order
                    if seq < lane.done_seq:
                        lane.ooo_replies += 1
                    elif seq > lane.done_seq:
                        lane.done_seq = seq
                if light is None:
                    # untracked completion: a duplicate delivery (the
                    # spill RPC's timeout path may re-send records whose
                    # first copy DID land) or a task the break-lane /
                    # cancel recovery already owns — both are no-ops here
                    # (at-least-once delivery, exactly-once application)
                    entry = self.memory_store.get(oid)
                    if entry is None or entry.ready.is_set():
                        continue
                if (stamp is not None and ent is not None and ent[1]
                        and status != fastpath.NEED_SLOW):
                    # ONE raw tuple store per task — stamp decoding,
                    # percentile math and shm SAMPLE slots all happen on
                    # the flush timer over bounded windows, never here.
                    # Actor calls land in their own window so the stage
                    # breakdown surfaces as actor_* rows beside the task
                    # rows in state.list_task_latency().
                    if task_id.is_actor_task():
                        if astats is not None:
                            astats.ring[astats.n % astats.cap] = (
                                ent[1], t_rx, tid_b, stamp)
                            astats.n += 1
                    elif stats is not None:
                        sring[stats.n % scap] = (ent[1], t_rx, tid_b, stamp)
                        stats.n += 1
                if light is not None and light[0] == "serve":
                    # untracked serve call: the waiter resolution above
                    # IS the completion — no entry, no events, no
                    # migrate bookkeeping. Only a shm-sealed result
                    # needs the owned-object plane (entry created here,
                    # ref minted by fast_actor_await); a NEED_SLOW means
                    # the worker's method table went stale — retire the
                    # lane (outside the cv) exactly like the tracked
                    # path would, the waiters re-dispatch over RPC.
                    if status == fastpath.NEED_SLOW:
                        retire_serve = lane
                    elif status == fastpath.OK_SHM:
                        if oid not in self.memory_store:
                            self.memory_store[oid] = _MemEntry()
                        self._fast_done[oid] = (status, payload)
                        batch.append((task_id, oid, status, payload, light))
                    continue
                if status != fastpath.NEED_SLOW:
                    self._fast_done[oid] = (status, payload)
                batch.append((task_id, oid, status, payload, light))
            if (not lane.inflight and lane.drain_waiters
                    and lane.drain_evt is not None):
                # wake RPC-fallback calls parked on the drain barrier —
                # gated on drain_waiters so the pure-ring round trip
                # never pays this loop self-pipe wake
                drained = True
            self._fast_migrate_q.extend(batch)
            arm = not self._fast_migrate_armed
            if arm:
                self._fast_migrate_armed = True
            self._fast_cv.notify_all()
        if wake:
            self._queue_loop_wakes(wake)
        if tspans is not None:
            self._trace_apply_replies(tspans)
        if retire_serve is not None:
            self._fast_retire_actor_lane(retire_serve)
        if drained:
            try:
                self.loop.call_soon_threadsafe(lane.drain_evt.set)
            except RuntimeError:
                pass  # loop gone (shutdown)
        if arm:
            try:
                self.loop.call_soon_threadsafe(self._drain_fast_migrations)
            except RuntimeError:
                pass  # loop gone (shutdown)

    async def rpc_fast_result(self, conn, p):
        """Result-ring spill receiver: completion records the worker could
        not push into a full result ring arrive here over RPC (the slow
        road backs the fast lane in both directions). Records whose task
        is no longer tracked on a lane (break-lane recovery or cancel got
        there first) are dropped — the RPC resubmission owns them."""
        from ray_tpu.core import fastpath

        by_lane: dict[int, tuple] = {}
        with self._fast_cv:
            for rec in p["records"]:
                if rec[:1] == b"G":
                    # spilled stream chunk: route on the sink's lane
                    # (chunks are untracked — no _fast_oid_lane entry
                    # pops for them, the terminal owns that)
                    g = fastpath.unpack_chunk(rec)
                    if g is not None:
                        soid = ObjectID.for_task_return(TaskID(g[0]), 0)
                        sink = (self._fast_stream_sinks.get(soid)
                                or self._fast_stream_dead.get(soid))
                        if sink is not None:
                            lane = sink.lane
                            by_lane.setdefault(
                                id(lane), (lane, []))[1].append(rec)
                            continue
                    try:
                        tid_b = fastpath.unpack_reply(rec)[0]
                    except Exception:
                        continue  # ownerless chunk: drop
                else:
                    tid_b = fastpath.unpack_reply(rec)[0]
                oid = ObjectID.for_task_return(TaskID(tid_b), 0)
                ent = self._fast_oid_lane.get(oid)
                if ent is not None:
                    lane = ent[0]
                    by_lane.setdefault(id(lane), (lane, []))[1].append(rec)
        for lane, recs in by_lane.values():
            self._fast_spilled_results += len(recs)
            self._fast_process_replies(lane, recs)
        return True

    def _drain_fast_migrations(self):
        """Loop-side completion: fill memory-store entries, emit events,
        resubmit NEED_SLOW tasks via the RPC path.

        Lingers on a 2ms timer while reply traffic flows (stays armed, so
        reply processors never pay a self-pipe wake per batch — on a
        one-core host that wake lands between the caller and the worker);
        disarms after one empty pass."""
        from ray_tpu.core import fastpath

        with self._fast_cv:
            batch = self._fast_migrate_q
            self._fast_migrate_q = []
            if not batch:
                self._fast_migrate_armed = False
                return
            # armed stays True while this pass runs; the tail decides
            # between timer-linger (blocking-call traffic) and disarm
            # (burst traffic) — see below
        lanes_to_check = set()
        result_bytes: dict = {}
        for task_id, oid, status, payload, light in batch:
            if status == fastpath.NEED_SLOW:
                if light is not None:
                    if light[0] == "actor":
                        # worker-side NEED_SLOW: a method the shipped
                        # eligibility table didn't cover (dynamically
                        # added / stale table). The worker NEED_SLOWed
                        # the whole in-flight tail in ring order, so
                        # retiring here keeps FIFO; driver-visible
                        # ineligibility (ref args, generators, option
                        # overrides) never reaches this path — those
                        # fall back per CALL and the lane lives on
                        lane = self._fast_actor_lanes.get(light[1])
                        if lane is not None:
                            self._fast_retire_actor_lane(lane)
                    else:
                        self._fast_ineligible_funcs.add(
                            getattr(light[0], "__rt_func_id__", b""))
                    # NEED_SLOW is a migration, not a loss: the worker
                    # declined without executing, so the full budget rides
                    self._fast_resubmit(task_id, light, lost=False)
                continue
            entry = self.memory_store.get(oid)
            if light is None:
                name = "task"
                if entry is None or entry.ready.is_set():
                    # duplicate delivery that slipped past the intake
                    # guard (first copy drained in between): the value,
                    # events and metrics were all applied already
                    continue
            elif light[0] in ("actor", "serve"):
                name = light[2]
            else:
                name = getattr(light[0], "__name__", "task")
            if entry is not None and not entry.ready.is_set():
                if status == fastpath.OK:
                    entry.packed = payload
                elif status == fastpath.OK_SHM:
                    entry.in_shm = True
                    # the completion record IS the location registration
                    # for the cache (the GCS directory write below stays
                    # the source of truth): shm-ring lanes are same-node,
                    # tunnel lanes carry the sealing node in the shm
                    # descriptor (pack_shm_desc); its size payload feeds
                    # the task event below
                    size, holder = fastpath.unpack_shm_desc(payload)
                    result_bytes[oid] = size
                    holder = holder or self.node_id.binary()
                    self._obj_locations.setdefault(oid, set()).add(holder)
                    if light is not None and light[0] not in ("actor",
                                                              "serve"):
                        # shm results can be evicted: keep real lineage
                        # (actor calls have no reconstruction, as in the
                        # reference — actor state is not replayable). The
                        # task COMPLETED, so reconstruction gets the full
                        # user budget back
                        budget = light[4]
                        if budget is None:
                            budget = self.cfg.default_max_task_retries
                        self._lineage[task_id] = self._fast_light_to_spec(
                            task_id, light, budget)
                        self._lineage_live[task_id] = {oid}
                    self._bg.spawn(self._register_location(oid, holder),
                                   self.loop)
                else:  # ERR
                    try:
                        entry.error = pickle.loads(payload)
                    except Exception as e:  # unpicklable error payload
                        entry.error = TaskError(f"task failed: {e!r}")
                entry.ready.set()
            self._cancelled_tasks.discard(task_id)
            outcome = "failed" if status == fastpath.ERR else "ok"
            metrics.tasks_finished.inc(tags={"outcome": outcome})
            ev = dict(task_id=task_id.hex(), name=name,
                      state="FAILED" if status == fastpath.ERR
                      else "FINISHED")
            size = result_bytes.get(oid)
            if size:
                ev["result_bytes"] = size  # shm-sealed result size
            self.task_events.emit(**ev)
            with self._fast_cv:
                self._fast_done.pop(oid, None)
        # a RETIRED actor lane whose in-flight records have all drained is
        # finished forever (permanent RPC downgrade): close its ring so
        # the worker's executor-resident pump cycle stops — otherwise it
        # would keep taking 5ms slices of the actor thread ahead of every
        # RPC-path call for the actor's lifetime
        for lane in list(self._fast_lanes):
            if (lane.retired and not lane.broken and not lane.inflight
                    and lane.key and lane.key[0] == "actor"):
                self._fast_break_lane(lane)
        # a drained lane's lease must still be returnable when idle; arm at
        # most one idle-return watcher per lane drain-down
        drained = False
        for lane in [ln for ln in self._fast_lanes if not ln.inflight]:
            state = self.sched_keys.get(lane.key)
            if state is None:
                continue
            drained = True
            state.fast_backlog_since = 0.0  # drained: demand pressure gone
            if not lane.return_armed and lane.worker in state.workers:
                lane.return_armed = True
                self._bg.spawn(
                    self._fast_idle_return(lane, state), self.loop)
        if drained:
            self._report_demand()  # clear any stale nonzero raylet report
        # Adaptive linger. Blocking-call traffic (submit/get/submit/get —
        # one reply per pass) lingers on a sleepy 2ms timer: staying
        # armed means the reply processor never pays a self-pipe wake,
        # which on a one-core host lands on the critical path between
        # caller and worker (~25% of the sync-call round trip). Burst
        # traffic (pipelined gets, many replies per pass) disarms
        # instead: there the wake amortizes over the whole batch and the
        # 2ms pacing throttles the pipeline.
        if len(batch) < 8:
            self.loop.call_later(0.002, self._drain_fast_migrations)
        else:
            with self._fast_cv:
                refilled = bool(self._fast_migrate_q)
                if not refilled:
                    self._fast_migrate_armed = False
            if refilled:  # stay armed; immediate re-pass, no recursion
                self.loop.call_soon(self._drain_fast_migrations)

    async def _fast_idle_return(self, lane, state):
        try:
            await self._maybe_return_lease(lane.key, state, lane.worker)
        finally:
            lane.return_armed = False

    def _fast_light_to_spec(self, task_id: TaskID, light,
                            budget: int) -> dict:
        """Expand a fast-path lineage tuple into a full RPC task spec
        (reusing the already-issued task id: its refs are in user hands).
        ``budget`` is the remaining retry allowance — _fast_resubmit
        resolves it from the tuple's user max_retries, charging one loss
        only when a worker actually died (chaos kill schedules exposed
        the earlier config-default reset)."""
        fn, args, kwargs, resources, _max_retries = light
        return {
            "task_id": task_id,
            "name": getattr(fn, "__name__", "task"),
            "func_id": fn.__rt_func_id__,
            "language": "python",
            "func_name": None,
            "args": list(args),
            "kwargs": dict(kwargs),
            "num_returns": 1,
            "resources": dict(resources),
            "owner_address": self.address,
            "max_retries": max(0, budget),
            "placement_group": None,
            "bundle_index": -1,
            "scheduling_node": None,
            "runtime_env": self.default_runtime_env,
        }

    def _fast_retire_actor_lane(self, lane) -> None:
        """Permanent RPC downgrade of an actor lane. Since 1.8 only a
        worker-side NEED_SLOW (method missing from the shipped
        eligibility table) lands here — driver-visible ineligibility
        falls back per call. When nothing is in flight the ring closes
        right away so the worker's executor-resident pump cycle stops;
        otherwise the drain path closes it once the last reply lands."""
        lane.retired = True
        with self._fast_cv:
            drained = not lane.inflight and not lane.broken
        if drained:
            self._fast_break_lane(lane)

    def _fast_try_retire_lane(self, lane) -> bool:
        """Idle-lease-return teardown: atomically stop new fast submits
        and confirm nothing is in flight. A worker being retired is ALIVE
        — its pump drains the ring before exiting — so the break-lane
        resubmission path must never fire here (a task both drained and
        resubmitted would execute twice). Returns False (lane stays live)
        if a racing submit got in between the idle check and the break."""
        with self._fast_cv:
            if not lane.broken:
                if lane.inflight:
                    return False
                lane.broken = True
        self._fast_break_lane(lane)  # leftovers empty by construction
        return True

    def _fast_break_lane(self, lane):
        """Thread-safe: stop routing to this lane and resubmit whatever is
        in flight through the RPC path (worker death / lease return)."""
        wake = []
        with self._fast_cv:
            if lane.broken:
                leftovers = {}
            else:
                lane.broken = True
                leftovers = dict(lane.inflight)
                lane.inflight.clear()
                for task_id, light in leftovers.items():
                    oid = ObjectID.for_task_return(task_id, 0)
                    self._fast_oid_lane.pop(oid, None)
                    if self._tunnel_pins:
                        self._tunnel_pins.pop(task_id, None)
                    if self._trace_pending and light[0] == "serve":
                        # untracked serve call dying with the lane: its
                        # ::call span will never stamp (the router's RPC
                        # replay mints a fresh submit span); tracked
                        # entries stay for _fast_resubmit's ctx handoff
                        self._trace_pending.pop(oid, None)
                    fut = self._fast_loop_waiters.pop(oid, None)
                    if fut is not None:
                        # broken mid-flight: fast_actor_await raises
                        # ConnectionLost, the router's policy owns replay
                        wake.append((fut, None, None, oid))
                    if self._fast_stream_sinks:
                        sink = self._fast_stream_sinks.pop(oid, None)
                        if sink is not None:
                            # stream dying with the lane: the broken
                            # sentinel ends iteration with
                            # ConnectionLost — chunks already consumed
                            # are never replayed
                            wake.append((sink, None, None, oid))
            self._fast_cv.notify_all()
        if wake:
            self._queue_loop_wakes(wake)
        if lane.drain_evt is not None and lane.drain_waiters:
            try:  # nothing is in flight on a broken lane: wake drain waiters
                self.loop.call_soon_threadsafe(lane.drain_evt.set)
            except RuntimeError:
                pass  # loop gone (shutdown)
        with lane.txlock:
            # buffered records were in the inflight snapshot above (or in
            # an earlier break's): the RPC resubmission owns them now
            lane.txbuf.clear()
            lane.txbytes = 0
        if lane.worker is not None and lane.worker.fast_lane is lane:
            lane.worker.fast_lane = None
        if lane.key and lane.key[0] == "actor":
            if self._fast_actor_lanes.get(lane.key[1]) is lane:
                self._fast_actor_lanes.pop(lane.key[1], None)
        if lane in self._fast_lanes:
            try:
                self._fast_lanes.remove(lane)
            except ValueError:
                pass
        lane.ring.close(0)
        lane.ring.close(1)
        if leftovers and not self._closed:
            def resub():
                for task_id, light in leftovers.items():
                    if task_id in self._cancelled_tasks:
                        continue  # entries already failed by cancel_task
                    if light[0] == "serve":
                        # untracked: the broken-sentinel wake above told
                        # the router, whose retry_on gate owns replay —
                        # core resubmission would re-execute
                        # non-idempotent requests behind its back
                        continue
                    self._fast_resubmit(task_id, light)
            try:
                self.loop.call_soon_threadsafe(resub)
            except RuntimeError:
                pass

    async def _fast_health_loop(self):
        """Worker death with an empty loop (nobody mid-RPC to notice):
        sweep lanes whose worker connection died and recover their
        tasks. Doubles as the tunnel-lane revival driver: actors that
        lost their tunnel lane (tunnel break, raylet restart) re-bind
        here once the redial lands — until then their calls ride the
        per-call RPC fallback."""
        while not self._closed:
            await asyncio.sleep(2.0)
            for lane in list(self._fast_lanes):
                if lane.broken:
                    continue
                w = lane.worker
                if w.conn is None or w.conn._closed or lane.ring.is_closed(1):
                    self._fast_break_lane(lane)
            if self._tunnel_ok() and self._tunnel_actor_seen:
                for actor_id in list(self._tunnel_actor_seen):
                    if actor_id in self._fast_actor_lanes:
                        continue
                    conn = self._actor_conns.get(actor_id)
                    if conn is None or conn._closed:
                        continue  # next RPC dial re-attaches anyway
                    self._bg.spawn(
                        self._tunnel_actor_attach(actor_id, conn),
                        self.loop)

    def fast_prepass(self, refs, timeout: float | None) -> dict:
        """Blocking wait (user thread) for fast-path refs, resolved straight
        from the reply stream. Returns {oid: ("v", packed) | ("e", exc)};
        refs it does not resolve (slow, shm, timed out) are left for the
        normal get path."""
        if not self._fast_oid_lane and not self._fast_done:
            return {}
        from ray_tpu.core import fastpath

        # about to block on results: push any coalesced submit tail now
        # rather than waiting out the flusher's linger
        for lane in list(self._fast_lanes):
            if lane.txbytes and not lane.broken:
                self._fast_flush_lane(lane, timeout_ms=20)
        deadline = None if timeout is None else time.monotonic() + timeout
        resolved: dict = {}
        while True:
            steal_lane = None
            with self._fast_cv:
                pending = set()
                lanes = set()
                for r in refs:
                    oid = r.id
                    if oid in resolved:
                        continue
                    hit = self._fast_done.get(oid)
                    if hit is not None:
                        resolved[oid] = hit
                        continue
                    ent = self._fast_oid_lane.get(oid)
                    if ent is None:
                        continue  # migrated/broken/cancelled: loop path owns it
                    entry = self.memory_store.get(oid)
                    if entry is not None and entry.ready.is_set():
                        continue  # completed via the loop
                    pending.add(oid)
                    lanes.add(ent[0])
                if not pending:
                    break
                if len(lanes) == 1:
                    steal_lane = next(iter(lanes))
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                break
            # Single-lane wait: become the reply-ring consumer ourselves —
            # the result then costs one thread wake (worker pump -> us)
            # instead of three (pump -> sweeper -> cv -> us). Tunnel
            # lanes have no ring to steal (replies arrive on the loop):
            # they take the cv wait below, woken per reply batch.
            if (steal_lane is not None and not steal_lane.broken
                    and not getattr(steal_lane.ring, "tunnel", False)):
                steal_lane.user_wants = time.monotonic()
                if steal_lane.rx_lock.acquire(blocking=False):
                    try:
                        pop_ms = int(1000 * min(0.2, remaining or 0.2))
                        recs = steal_lane.ring.pop_batch(
                            fastpath.REP, max(1, pop_ms))
                    finally:
                        steal_lane.rx_lock.release()
                    if recs is None:
                        self._fast_break_lane(steal_lane)
                    elif recs:
                        self._fast_process_replies(steal_lane, recs)
                    continue
            # sweeper-consumed (or multi-lane) wait; bounded because
            # loop-side completions (cancel, slow takeover) don't notify
            with self._fast_cv:
                again = any(oid in self._fast_done for oid in pending)
                if not again:
                    self._fast_cv.wait(
                        0.05 if remaining is None else min(0.05, remaining))
        out = {}
        for oid, (status, payload) in resolved.items():
            from ray_tpu.core import fastpath
            if status == fastpath.OK:
                out[oid] = ("v", payload)
            elif status == fastpath.ERR:
                try:
                    out[oid] = ("e", pickle.loads(payload))
                except Exception as e:
                    out[oid] = ("e", TaskError(f"task failed: {e!r}"))
            elif status == fastpath.OK_SHM and self.store is not None:
                # the worker sealed the result into the local arena before
                # replying: read it zero-copy right here on the caller
                # thread instead of waiting out the loop migration
                hit = self.store.try_get(oid)
                if hit is not None:
                    out[oid] = ("V", hit[0])
                # else evicted/racing: the normal path pulls/rebuilds
        return out

    def get_local_prepass(self, refs) -> dict:
        """Caller-thread get: resolve refs whose values are already local —
        ready memory-store entries unpack in place, sealed local shm
        objects read zero-copy through the arena mapping — WITHOUT the
        event-loop round trip the async path pays per call. Never blocks;
        anything unresolved (pending, remote, evicted) is left for
        get_async, which stays the source of truth. Returns
        {oid: ("V", value) | ("e", exc)}."""
        out: dict = {}
        store = self.store
        for ref in refs:
            oid = ref.id
            if oid in out:
                continue
            entry = self.memory_store.get(oid)
            if entry is None or not entry.ready.is_set():
                continue
            if entry.error is not None:
                out[oid] = ("e", entry.error)
                continue
            if not entry.in_shm:
                try:
                    if entry.packed is not None:
                        out[oid] = ("V", serialization.unpack(entry.packed))
                    else:
                        out[oid] = ("V", entry.value)
                except Exception:
                    continue  # let the slow path surface the failure
                continue
            if store is not None:
                hit = store.try_get(oid)
                if hit is not None:
                    out[oid] = ("V", hit[0])
                # absent/pending/evicted: the async pull path owns it
        return out

    def fast_wait_prepass(self, refs, num_returns: int,
                          timeout: float | None):
        """Caller-thread wait. Ready refs (memory-store entries, local shm
        objects, fast-lane completions) are counted without touching the
        event loop; when the shortfall consists ENTIRELY of fast-lane
        in-flight refs, block on the reply-stream condition variable —
        completions wake it directly — instead of parking watcher tasks on
        the loop. Returns (ready, pending) in ref order, or None when some
        pending ref needs the loop path (borrowed refs, RPC-path tasks:
        wait_async owns those blocking semantics)."""
        if _in_loop(self.loop):
            return None  # loop thread: _run_sync's guard owns the error
        refs = list(refs)
        # wait never runs the get prepass: push any coalesced submit tail
        # now rather than waiting out the flusher's linger
        for lane in list(self._fast_lanes):
            if lane.txbytes and not lane.broken:
                self._fast_flush_lane(lane, timeout_ms=20)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready_idx: set[int] = set()
            shortfall_fast = True
            for i, ref in enumerate(refs):
                if len(ready_idx) >= num_returns:
                    break
                entry = self.memory_store.get(ref.id)
                if entry is not None and entry.ready.is_set():
                    ready_idx.add(i)
                elif entry is None and self.store is not None \
                        and self.store.contains(ref.id):
                    ready_idx.add(i)
                # lock-free membership probes (GIL-atomic): taking
                # _fast_cv per ref would cost O(n) lock round-trips per
                # scan against the reply threads; a racy miss just makes
                # this round conservative — the next round (or the loop
                # path) resolves it
                elif ref.id in self._fast_done:
                    ready_idx.add(i)
                elif ref.id not in self._fast_oid_lane:
                    shortfall_fast = False
            if len(ready_idx) >= num_returns:
                ready = [r for i, r in enumerate(refs) if i in ready_idx]
                pending = [r for i, r in enumerate(refs)
                           if i not in ready_idx]
                return ready, pending
            if not shortfall_fast:
                return None  # loop path owns the blocking wait
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                ready = [r for i, r in enumerate(refs) if i in ready_idx]
                pending = [r for i, r in enumerate(refs)
                           if i not in ready_idx]
                return ready, pending
            with self._fast_cv:
                self._fast_cv.wait(
                    0.05 if remaining is None else min(0.05, remaining))

    # ------------------------------------------------------ task submission
    def _register_function(self, fn) -> bytes:
        """Export the function blob to the GCS function table once
        (ref: remote_function.py pickled-function export). Registration is
        fire-and-forget: executors retry the table fetch briefly, so a task
        can never race ahead of its own function blob for long."""
        cached = getattr(fn, "__rt_func_id__", None)
        if cached is not None and cached in self._registered_funcs:
            return cached
        blob = serialization.ship_dumps(fn)
        func_id = hashlib.sha1(blob).digest()
        if func_id not in self._registered_funcs:
            self._call_on_loop(
                self.gcs.call(
                    "kv_put",
                    {"ns": "funcs", "key": func_id.hex(), "value": blob, "overwrite": False},
                )
            )
            self._registered_funcs.add(func_id)
        try:
            fn.__rt_func_id__ = func_id
            # plain sync callables qualify for the shm-ring fast path;
            # generators/coroutines need the RPC streaming machinery
            fn.__rt_fast_ok__ = not (
                inspect.iscoroutinefunction(fn)
                or inspect.isgeneratorfunction(fn)
                or inspect.isasyncgenfunction(fn))
        except (AttributeError, TypeError):
            pass
        return func_id

    def submit_template(self, tmpl, fn, args, kwargs):
        """Flat steady-state submit: everything a .remote() call used to
        re-derive per call (resources dict, normalized strategy, placement
        target, scheduling key, function registration) comes precomputed
        in the frozen SubmitTemplate (core/api.py). Fast-eligible calls go
        straight into the ring with the template's key; everything else —
        and every fast miss — falls through to submit_task, which stays
        the single source of truth for slow-path semantics and builds a
        spec byte-identical to a direct submit_task call."""
        if tmpl.fast_ok:
            ref = self._fast_submit_keyed(fn, tmpl.func_id, tmpl.sched_key,
                                          tmpl.resources, args, kwargs,
                                          max_retries=tmpl.max_retries)
            if ref is not None:
                return ref
        return self.submit_task(
            fn, args, kwargs,
            num_returns=tmpl.num_returns,
            resources=dict(tmpl.resources),
            max_retries=tmpl.max_retries,
            placement_group=tmpl.placement_group,
            bundle_index=tmpl.bundle_index,
            scheduling_node=tmpl.scheduling_node,
            scheduling_strategy=tmpl.scheduling_strategy,
            name=tmpl.name,
            runtime_env=tmpl.runtime_env,
            _fast_tried=True,
        )

    def submit_task(self, fn, args, kwargs, *, num_returns=1, resources=None,
                    max_retries=None, placement_group=None, bundle_index=-1,
                    scheduling_node=None, scheduling_strategy=None, name=None,
                    runtime_env=None,
                    _fast_tried=False) -> list[ObjectRef] | ObjectRef:
        """Synchronous entry (driver thread) or loop-thread entry (nested).

        ``fn`` is a Python callable, or ("cpp", func_name) for cross-language
        submission to a C++ worker (ref: cpp/ worker API; function resolved
        from the binary's RT_REMOTE registry by name). ``_fast_tried``
        (internal, set by submit_template) records that the ring fast path
        was already attempted this call, so the burst detector isn't
        double-counted; it never affects the built task spec."""
        language = "python"
        func_name = None
        if isinstance(fn, tuple) and len(fn) == 2 and fn[0] == "cpp":
            language, func_name = "cpp", fn[1]
            if kwargs:
                raise TypeError("C++ tasks take positional arguments only")
            func_id = b"cpp:" + func_name.encode()
        else:
            if (not _fast_tried and num_returns == 1
                    and placement_group is None
                    and scheduling_node is None and runtime_env is None
                    and scheduling_strategy is None
                    and name is None):
                ref = self._try_fast_submit(
                    fn, args, kwargs, dict(resources or {"CPU": 1.0}),
                    max_retries=max_retries)
                if ref is not None:
                    return ref
            func_id = self._register_function(fn)
        self._task_counter += 1
        task_id = TaskID.generate()
        resources = dict(resources or {"CPU": 1.0})
        spec = {
            "task_id": task_id,
            "name": name or func_name or getattr(fn, "__name__", "task"),
            "func_id": func_id,
            "language": language,
            "func_name": func_name,
            "args": args,
            "kwargs": kwargs,
            "num_returns": num_returns,
            "resources": resources,
            "owner_address": self.address,
            "max_retries": self.cfg.default_max_task_retries if max_retries is None else max_retries,
            "placement_group": placement_group,
            "bundle_index": bundle_index,
            "scheduling_node": scheduling_node,
            "scheduling_strategy": scheduling_strategy,
            "runtime_env": self._resolve_runtime_env(runtime_env),
        }
        metrics.tasks_submitted.inc()
        self.task_events.emit(task_id=task_id.hex(), name=spec["name"],
                              state="PENDING_ARGS_AVAIL")
        if self.cfg.tracing_enabled:
            self._emit_submit_span(spec, spec["name"])
        if num_returns == "streaming":
            self._gen_states[task_id] = _GenState()
            self._call_on_loop(self._submit_async(spec))
            return ObjectRefGenerator(task_id, self)
        # lineage stash BEFORE _submit_async mutates args in place: the
        # original arg refs are pinned so lost returns can re-execute
        # (ref: task_manager.h:182, object_recovery_manager.h:43)
        self._lineage[task_id] = {
            **spec, "args": tuple(args), "kwargs": dict(kwargs),
        }
        self._lineage_live[task_id] = {
            ObjectID.for_task_return(task_id, i) for i in range(num_returns)
        }
        if len(self._lineage) > 10_000:
            old = next(iter(self._lineage))
            self._lineage.pop(old)
            self._lineage_live.pop(old, None)
        refs = []
        for i in range(num_returns):
            roid = ObjectID.for_task_return(task_id, i)
            self.memory_store[roid] = _MemEntry()
            refs.append(self._new_owned_ref(roid))
        self._call_on_loop(self._submit_async(spec))
        return refs[0] if num_returns == 1 else refs

    def _emit_submit_span(self, spec: dict, name: str) -> None:
        """Record a point span for the .remote() call and inject its id as
        the parent for the executing side's child span (ref:
        tracing_helper.py:36-60 span-context injection into task specs).
        Head-sampled: an unsampled root gets no span and no trace_ctx."""
        from ray_tpu.utils import tracing

        parent = tracing.submit_context()
        if parent is None:
            return  # unsampled request: ship nothing, record nothing
        tid_hex = spec["task_id"].hex()
        submit_id = tracing.emit_point(
            f"{name}.remote", parent,
            lambda s: self.task_events.emit(
                task_id=tid_hex, name=s["name"], state="SPAN", span=s),
            stage="wire", transport="rpc")
        spec["trace_ctx"] = {"trace_id": parent["trace_id"],
                             "parent_span_id": submit_id}

    def _trace_submit_leg(self, task_id: TaskID, name: str,
                          transport: str) -> bytes:
        """Wire trace leg for one fast-lane submit (b"" = unsampled:
        the caller ships nothing). Sampled: mints the submit and call
        span ids, registers them keyed by the return oid, and returns
        the packed 25-byte context whose span_id is the CALL span — so
        the worker's exec span nests INSIDE the wire-level call span
        (the call span's self-time is then pure transport, never
        double-billing exec). NOTHING is emitted yet: both spans land
        at reply-apply, so a declined submit (RPC fallback) leaves no
        orphan markers and the fallback's own spans are the record."""
        from ray_tpu.utils import tracing

        # the head-sampling gate itself: returns None (no alloc
        # downstream) for unsampled requests, and a sampled submit
        # minting its trace leg IS the product
        ctx = tracing.submit_context()  # raylint: disable=RT023 -- sampling gate
        if ctx is None:
            return b""
        submit_id = tracing._gen_span_id()
        call_id = tracing._gen_span_id()
        pending = self._trace_pending
        if len(pending) > 4096:  # replies that never came (broken lanes)
            pending.pop(next(iter(pending)), None)
        oid = ObjectID.for_task_return(task_id, 0)
        pending[oid] = (ctx["trace_id"], ctx.get("parent_span_id"),
                        submit_id, call_id, name, time.time(), transport)
        return tracing.pack_ctx(ctx["trace_id"], call_id, True)

    def _trace_emit_submit_point(self, task_id: TaskID, tp) -> None:
        """Materialize the deferred submit point span (reply-apply, or
        an RPC resubmission that inherits the pending context)."""
        trace_id, parent0, submit_id, _, name, t_submit, transport = tp
        self.task_events.emit(
            task_id=task_id.hex(), name=f"{name}.remote", state="SPAN",
            span={
                "trace_id": trace_id, "span_id": submit_id,
                "parent_span_id": parent0, "name": f"{name}.remote",
                "start_ts": t_submit, "end_ts": t_submit,
                "stage": "wire", "transport": transport,
            })

    def _trace_apply_replies(self, tspans: list) -> None:
        """Reply-apply leg of wire tracing: for each sampled completion,
        materialize the submit point span and the ``<name>::call`` wire
        span (submit wall -> apply wall, span id PRE-MINTED at submit —
        the worker's ::run span is its child) with the stage stamp as
        attributes — the queue-vs-exec-vs-wire truth TraceCriticalPath
        consumes."""
        from ray_tpu.core import fastpath

        now = time.time()
        for oid, stamp, tp in tspans:
            trace_id, _, submit_id, call_id, name, t_submit, transport = tp
            task_id = oid.task_id()
            self._trace_emit_submit_point(task_id, tp)
            span = {
                "trace_id": trace_id,
                "span_id": call_id,
                "parent_span_id": submit_id,
                "name": f"{name}::call",
                "start_ts": t_submit, "end_ts": now,
                "stage": "wire", "transport": transport,
            }
            if stamp is not None:
                ring_ns, deser_ns, exec_ns = fastpath.unpack_stamp(stamp)
                span["ring_us"] = ring_ns / 1e3
                span["deser_us"] = deser_ns / 1e3
                span["exec_us"] = exec_ns / 1e3
            self.task_events.emit(
                task_id=task_id.hex(), name=span["name"],
                state="SPAN", span=span)

    def _call_on_loop(self, coro):
        """Run a coroutine (or apply a deleted-ref notice, passed as a bare
        ObjectID) on the loop thread, coalescing cross-thread wakeups.

        Two lanes: coroutines are latency-sensitive (an RPC-path sync
        call's submission rides here) and arm the drain immediately;
        deleted-ref notices are pure bookkeeping and ride a lazy 5ms
        timer, so a blocking-call loop (submit/get/submit/get...) never
        pays a loop wakeup per iteration just to decrement a refcount —
        on a one-core host every extra loop wake lands on the critical
        path between the caller and the worker."""
        if _in_loop(self.loop):
            if type(coro) is ObjectID:
                self._on_owned_ref_deleted_on_loop(coro)
            else:
                self._bg.spawn(coro, self.loop)
            return
        if type(coro) is ObjectID:
            with self._xq_lock:
                self._xq_lazy.append(coro)
                if self._xq_armed or self._xq_lazy_armed:
                    return  # an armed drain will sweep the lazy queue too
                self._xq_lazy_armed = True
            self.loop.call_soon_threadsafe(self._arm_lazy_xq)
            return
        # Coalesced thread->loop handoff: call_soon_threadsafe writes the
        # loop's self-pipe (a syscall) per call, so a burst of .remote()
        # submissions from the user thread pays one wakeup per task. Queue
        # instead and arm a single drain callback per burst.
        with self._xq_lock:
            self._xq.append(coro)
            arm = not self._xq_armed
            if arm:
                self._xq_armed = True
        if arm:
            self.loop.call_soon_threadsafe(self._drain_xq)

    def _arm_lazy_xq(self):
        self.loop.call_later(0.005, self._drain_xq)

    def _drain_xq(self):
        with self._xq_lock:
            lazy = self._xq_lazy
            self._xq_lazy = []
            if not self._xq and not lazy:
                # Linger one extra loop tick before disarming: during a
                # submission burst the producer refills between ticks, and
                # staying armed means it never pays the self-pipe wakeup.
                if self._xq_linger:
                    self._xq_linger = False
                    self.loop.call_soon(self._drain_xq)
                else:
                    self._xq_armed = False
                    self._xq_lazy_armed = False
                return
            batch = self._xq
            self._xq = []
            self._xq_linger = bool(batch)
        for oid in lazy:
            self._on_owned_ref_deleted_on_loop(oid)
        for coro in batch:
            if type(coro) is ObjectID:
                self._on_owned_ref_deleted_on_loop(coro)
            else:
                self._bg.spawn(coro, self.loop)
        if batch:
            # burst linger: immediate re-pass while coroutine traffic flows
            with self._xq_lock:
                self._xq_armed = True
                self._xq_lazy_armed = False
            self.loop.call_soon(self._drain_xq)
        else:
            # lazy-only traffic: stay armed on a sleepy timer instead of
            # busy-ticking the loop against the critical path
            with self._xq_lock:
                self._xq_lazy_armed = True
                self._xq_armed = False
            self.loop.call_later(0.005, self._drain_xq)

    async def _submit_async(self, spec: dict):
        try:
            pins: list = []
            spec["args"] = await self._resolve_args(spec["args"], pins)
            spec["kwargs"] = dict(
                zip(spec["kwargs"].keys(),
                    await self._resolve_args(list(spec["kwargs"].values()), pins))
            )
            if pins:
                self._inflight_pins[spec["task_id"]] = pins
        except Exception as e:
            self._complete_task_error(spec, e)
            return
        key = (
            spec["func_id"],
            tuple(sorted(spec["resources"].items())),
            spec.get("placement_group") and spec["placement_group"].hex(),
            spec.get("bundle_index"),
            spec.get("scheduling_node"),
            _strategy_key(spec.get("scheduling_strategy")),
        )
        state = self.sched_keys.setdefault(key, _SchedulingKeyState())
        state.strategy = spec.get("scheduling_strategy")
        state.inflight_tasks += 1
        await state.pending.put(spec)
        await self._pump(key, state)

    async def _resolve_args(self, args, pins: list | None = None):
        """Dependency resolution (ref: dependency_resolver.cc): owned inline
        args become values; everything else ships as a ref descriptor the
        executor fetches. ``pins`` collects every ObjectRef the args carry
        (top-level AND nested inside packed values) so the caller can keep
        them alive until the task completes — without this the owner could
        free an object while its ref is in flight to a slow-starting
        worker."""
        out = []
        for a in args:
            if isinstance(a, ObjectRef):
                if pins is not None:
                    pins.append(a)
                entry = self.memory_store.get(a.id)
                if entry is not None:
                    await entry.ready.wait()
                    if entry.error is not None:
                        raise entry.error
                    if not entry.in_shm:
                        packed = entry.packed
                        if packed is None:
                            meta, bufs = serialization.dumps_with_buffers(entry.value)
                            packed = _pack_bytes(meta, bufs, serialization.total_size(meta, bufs))
                        out.append(("v", packed))
                        continue
                self.note_ref_shipped(a.id)
                out.append(("r", a.id.binary(), a.owner_address))
            else:
                # pack through our serializer (cloudpickle fallback, jax/numpy
                # out-of-band) — the raw rpc frame uses plain pickle which
                # would choke on closures/jax values. No awaits between
                # setting and clearing _ship_collect: single loop thread.
                self._ship_collect = pins
                try:
                    packed = serialization.pack(a)
                finally:
                    self._ship_collect = None
                out.append(("v", packed))
        return out

    async def _pump(self, key, state: _SchedulingKeyState):
        """Dispatch pending tasks onto free leased workers; grow leases."""
        # hand tasks to free workers — a deep backlog rides one rpc frame
        # per worker turn (push_task_multi) instead of one frame per task.
        # The backlog is split across ALL free workers first (chunk), so a
        # small burst doesn't pile onto one worker and serialize.
        # a worker whose fast lane has tasks in flight is not free: its pump
        # thread is executing ring work, and an RPC batch on top would run
        # two tasks concurrently on a one-CPU lease
        # Prefer workers whose fast lane is quiet — an RPC batch on top of
        # in-flight ring work would run two tasks at once on a one-CPU
        # lease. Preference, not exclusion: when every lane is busy it is
        # still better to dispatch (brief oversubscription) than to starve
        # the batch and trigger a worker spawn that eats the only CPU.
        free = [w for w in state.workers if not w.busy]
        quiet = [w for w in free
                 if not (w.fast_lane is not None and w.fast_lane.inflight)]
        if quiet:
            free = quiet
        if free and not state.pending.empty():
            # chunk the backlog over free workers PLUS the leases we could
            # still grow into: a batch is committed to its worker, so
            # handing one worker everything would leave nothing for workers
            # a lease request is about to deliver (and then churn
            # spawn/idle/return on them)
            headroom = max(
                0,
                min(self.cfg.max_lease_parallelism, _NCPU)
                - len(state.workers),
            )
            targets = len(free) + headroom
            chunk = max(1, min(self.cfg.push_batch_size,
                               -(-state.pending.qsize() // targets)))
            if state.avg_task_s > 0.05:
                # long tasks: committing a deep batch to one worker would
                # serialize them and hide the backlog from lease growth,
                # spillback and the autoscaler — dispatch one at a time
                chunk = 1
            if (state.strategy or {}).get("type") == "spread":
                # SPREAD's whole point is one lease per node slice —
                # a deep batch on one worker would serialize the spread
                chunk = 1
            for w in free:
                if state.pending.empty():
                    break
                specs = [state.pending.get_nowait()]
                while len(specs) < chunk and not state.pending.empty():
                    specs.append(state.pending.get_nowait())
                w.busy = True
                self._bg.spawn(
                    self._run_on_worker(key, state, w, specs), self.loop)
        # grow leases in PARALLEL with backlog depth (ref:
        # normal_task_submitter pipelined RequestWorkerLease): a deep burst
        # must not pay one sequential worker-spawn per task. Bounded by
        # host cores — concurrent python worker spawns are CPU-hungry and
        # over-forking on small machines slows everything down.
        spawn_cap = _NCPU
        # demand = work still in the queue (the chunking above deliberately
        # leaves backlog in pending when lease headroom exists, so this
        # signal stays live for deep bursts — and goes quiet for small
        # bursts fully committed to live workers, avoiding spawn churn),
        # plus ring-queued fast tasks beyond one-per-worker — but only
        # once that backlog persisted (micro-bursts drain in milliseconds
        # and must not trigger worker spawns that eat their CPU)
        fast_backlog = 0
        if (state.fast_backlog_since
                and time.monotonic() - state.fast_backlog_since > 0.5):
            fast_backlog = sum(
                max(0, len(w.fast_lane.inflight) - 1)
                for w in state.workers if w.fast_lane is not None)
        want = min(
            state.pending.qsize() + fast_backlog
            - state.lease_requests_inflight,
            self.cfg.max_lease_parallelism - state.lease_requests_inflight,
            spawn_cap - state.lease_requests_inflight,
        )
        for _ in range(max(0, want)):
            state.lease_requests_inflight += 1
            self._bg.spawn(self._request_lease(key, state), self.loop)
        self._report_demand()

    def _report_demand(self):
        """Tell our raylet how much work is queued that no live lease or
        in-flight lease request will absorb, so unsatisfiable backlog is
        visible to the autoscaler even when this driver stops requesting
        leases (ref: autoscaler v2 resource-demand reporting). Coalesced
        and only sent on change."""
        now = time.monotonic()
        total = 0
        for state in self.sched_keys.values():
            backlog = state.pending.qsize()
            durable = (state.fast_backlog_since
                       and now - state.fast_backlog_since > 0.5)
            for w in state.workers:
                if w.fast_lane is not None and durable:
                    backlog += max(0, len(w.fast_lane.inflight) - 1)
                backlog += max(0, w.queued - 1)  # committed beyond executing
            total += max(0, backlog - state.lease_requests_inflight)
        if total == getattr(self, "_last_demand_report", 0):
            return
        self._last_demand_report = total
        if self.raylet is not None and not self.raylet._closed:
            self._bg.spawn(
                self.raylet.call("report_demand", {"count": total}),
                self.loop)

    async def _request_lease(self, key, state: _SchedulingKeyState):
        try:
            resources = dict(key[1])
            pg_hex = key[2]
            payload = {
                "resources": resources,
                "pg_id": None,
                "bundle_index": key[3],
                # cpp func_ids are b"cpp:<name>"; the raylet pools and
                # spawns workers per language (ref: worker_pool.h:231)
                "language": "cpp" if key[0].startswith(b"cpp:") else "python",
            }
            if pg_hex:
                from ray_tpu.utils.ids import PlacementGroupID

                payload["pg_id"] = PlacementGroupID.from_hex(pg_hex)
            raylet_addr = self.raylet_address
            target_node = key[4]
            strategy = state.strategy
            if strategy is not None:
                if strategy["type"] == "node_affinity":
                    # resolved address cached per scheduling key (stable
                    # while the node lives); cleared on lease failure so
                    # a died-and-replaced node re-resolves
                    addr = state.affinity_addr
                    if addr is None:
                        addr = await self._node_address(strategy["node_id"])
                        state.affinity_addr = addr
                    if addr is not None:
                        raylet_addr = tuple(addr)
                        if not strategy.get("soft"):
                            payload["no_spill"] = True
                    elif not strategy.get("soft"):
                        raise SchedulingError(
                            f"node {strategy['node_id']} required by "
                            "NodeAffinitySchedulingStrategy(soft=False) is "
                            "not alive")
                    # soft + node gone: fall back to the default policy
                else:
                    payload["strategy"] = strategy
            if target_node is not None:
                payload["no_spill"] = True
                raylet_addr = tuple(target_node)
            for _ in range(16):  # follow spillback chain
                conn = (
                    self.raylet
                    if tuple(raylet_addr) == tuple(self.raylet_address)
                    else await rpc.connect(*raylet_addr)
                )
                try:
                    # persistent conn → raylet may reap the lease if we die
                    payload["owner_bound"] = conn is self.raylet
                    reply = await conn.call("lease_worker", payload)
                finally:
                    if conn is not self.raylet:
                        await conn.close()
                if reply.get("infeasible"):
                    raise SchedulingError(
                        reply.get("error") or "no node satisfies the "
                        "task's scheduling strategy")
                if reply.get("drop_strategy"):
                    # strategy already satisfied by the redirect target
                    # (e.g. SPREAD chose it): it should grant locally
                    payload.pop("strategy", None)
                if reply.get("granted"):
                    w = _LeasedWorker(
                        lease_id=reply["lease_id"],
                        address=tuple(reply["worker_address"]),
                        worker_id=reply["worker_id"],
                        raylet_address=tuple(raylet_addr),
                        tpu_chips=reply.get("tpu_chips"),
                    )
                    w.conn = await rpc.connect(*w.address)
                    state.workers.append(w)
                    state.lease_failures = 0
                    state.lease_failure_sig = None
                    if (self.cfg.fastpath_enabled
                            and self.store is not None
                            and payload["language"] == "python"
                            and pg_hex is None):
                        same = (tuple(raylet_addr)
                                == tuple(self.raylet_address))
                        if same and not self.cfg.tunnel_force:
                            self._bg.spawn(
                                self._fast_attach(key, state, w), self.loop)
                        elif self._tunnel_ok():
                            # spilled-back / affinity lease on another
                            # node: "Q"/"R" records ride the node tunnel
                            self._bg.spawn(
                                self._tunnel_task_attach(key, state, w),
                                self.loop)
                    # arm the idle-return timer NOW: a lease granted after
                    # the backlog drained may never run a task, and the
                    # post-task timer alone would leak it (and its CPUs)
                    self._bg.spawn(self._maybe_return_lease(key, state, w), self.loop)
                    break
                raylet_addr = tuple(reply["spill_to"])
        except Exception as e:
            # A lease that keeps failing the SAME way with no workers to
            # show for it is a configuration problem (e.g. cpp task but no
            # RT_CPP_WORKER binary): fail the pending tasks instead of
            # spinning spawn->raise->pump forever. Guarded against one
            # transient hiccup failing several PARALLEL requests at once:
            # the error text must repeat, the failures must span real time
            # (> 2s, i.e. distinct attempts), and no lease may be live.
            now = time.monotonic()
            state.affinity_addr = None  # re-resolve after any failure
            # type-only signature: messages embed per-attempt detail
            # (ports, pids, paths) that must not defeat the breaker
            sig = type(e).__name__
            if sig != state.lease_failure_sig:
                state.lease_failure_sig = sig
                state.lease_failures = 1
                state.lease_failure_since = now
            else:
                state.lease_failures += 1
            # ConfigurationError is definitively non-transient (no worker
            # binary etc.): break immediately. Anything else — including
            # worker-start timeouts on a loaded box — gets a high threshold
            # and real elapsed time before we fail the pending tasks.
            is_config = isinstance(e, ConfigurationError)
            persistent = not state.workers and (
                is_config
                or (
                    state.lease_failures >= 10
                    and now - state.lease_failure_since > 15.0
                )
            )
            if persistent:
                err = e if isinstance(e, Exception) else TaskError(str(e))
                while not state.pending.empty():
                    spec = state.pending.get_nowait()
                    self._complete_task_error(spec, err)
                    state.inflight_tasks -= 1
                state.lease_failures = 0
                state.lease_failure_sig = None
            else:
                traceback.print_exc()
                # backoff so repeated transient failures (slow spawns) don't
                # hot-spin the pump → lease → raise loop
                await asyncio.sleep(min(0.2 * state.lease_failures, 2.0))
        finally:
            state.lease_requests_inflight -= 1
            await self._pump(key, state)

    async def _node_address(self, node_hex: str):
        """Resolve a node id (hex) to its raylet address via the GCS
        cluster view; None if the node is unknown or dead. GCS RPC
        failures propagate — a transient GCS hiccup must retry through
        the lease backoff path, not masquerade as a dead node and
        permanently fail hard-affinity tasks."""
        view = await self.gcs.call("get_cluster", {})
        for n in view:
            nid = n.get("node_id")
            nid_hex = nid.hex() if hasattr(nid, "hex") else str(nid)
            if nid_hex == node_hex and n.get("alive", True):
                return n.get("address")
        return None

    async def _run_on_worker(self, key, state, w: _LeasedWorker, specs: list):
        todo = []
        for spec in specs:
            if spec["task_id"] in self._cancelled_tasks:
                self._complete_task_error(
                    spec, TaskCancelledError(str(spec["task_id"])))
                state.inflight_tasks -= 1
            else:
                todo.append(spec)
        if not todo:
            w.busy = False
            w.idle_since = time.monotonic()
            await self._pump(key, state)
            self._bg.spawn(self._maybe_return_lease(key, state, w), self.loop)
            return
        for spec in todo:
            self.task_events.emit(task_id=spec["task_id"].hex(),
                                  name=spec["name"],
                                  state="SUBMITTED_TO_WORKER",
                                  worker_id=w.worker_id)
            self._task_worker[spec["task_id"]] = (
                w.raylet_address, w.worker_id, w.conn)
            if w.tpu_chips:
                spec["tpu_chips"] = w.tpu_chips
        done: list = []
        w.queued = len(todo)  # committed depth: demand accounting
        t_dispatch = time.monotonic()
        try:
            if len(todo) == 1 or key[0].startswith(b"cpp:"):
                # C++ workers speak the single-push protocol only (their
                # reader drops notification frames): pipeline sequentially
                for spec in todo:
                    done.append(
                        (spec, await w.conn.call("push_task", {"spec": spec})))
                    w.queued -= 1
            else:
                # one frame out, one reply per task back as each finishes
                futs = w.conn.call_scatter(
                    "push_task_multi", [{"spec": s} for s in todo])
                for idx, (spec, fut) in enumerate(zip(todo, futs)):
                    try:
                        done.append((spec, await fut))
                        w.queued -= 1
                    except rpc.ConnectionLost:
                        # later batch-mates may have RESOLVED before the
                        # connection died (replies arrive out of order):
                        # harvest those results, and consume the failed
                        # siblings' exceptions so asyncio doesn't log
                        # "exception was never retrieved" per task
                        lost = []
                        for s2, f2 in zip(todo[idx:], futs[idx:]):
                            if f2.done() and f2.exception() is None:
                                done.append((s2, f2.result()))
                            else:
                                if not f2.done():
                                    f2.cancel()
                                lost.append(s2)
                        # apply what completed, retry only the rest
                        for s2, reply in done:
                            self._task_worker.pop(s2["task_id"], None)
                            self._apply_task_reply(s2, reply)
                            state.inflight_tasks -= 1
                        for s2 in lost:
                            await self._on_worker_lost(key, state, w, s2)
                        return
        except rpc.ConnectionLost:
            # apply whatever completed before the drop (sequential path),
            # retry only the rest
            for s2, reply in done:
                self._task_worker.pop(s2["task_id"], None)
                self._apply_task_reply(s2, reply)
                state.inflight_tasks -= 1
            finished = {id(s) for s, _ in done}
            for spec in todo:
                if id(spec) not in finished:
                    await self._on_worker_lost(key, state, w, spec)
            return
        except Exception as e:
            # e.g. an unpicklable task spec: fail the tasks, free the worker
            for s2, reply in done:
                self._task_worker.pop(s2["task_id"], None)
                self._apply_task_reply(s2, reply)
                state.inflight_tasks -= 1
            finished = {id(s) for s, _ in done}
            for spec in todo:
                if id(spec) in finished:
                    continue
                self._task_worker.pop(spec["task_id"], None)
                self._complete_task_error(spec, e)
                state.inflight_tasks -= 1
            w.queued = 0
            w.busy = False
            w.idle_since = time.monotonic()
            await self._pump(key, state)
            self._bg.spawn(self._maybe_return_lease(key, state, w), self.loop)
            return
        for spec, reply in done:
            self._task_worker.pop(spec["task_id"], None)
            self._apply_task_reply(spec, reply)
            state.inflight_tasks -= 1
        w.queued = 0
        if done:
            per_task = (time.monotonic() - t_dispatch) / len(done)
            state.avg_task_s = (0.7 * state.avg_task_s + 0.3 * per_task
                                if state.avg_task_s else per_task)
        w.busy = False
        w.idle_since = time.monotonic()
        await self._pump(key, state)
        self._bg.spawn(self._maybe_return_lease(key, state, w), self.loop)

    def _apply_task_reply(self, spec, reply):
        task_id = spec["task_id"]
        self._inflight_pins.pop(task_id, None)
        self._cancelled_tasks.discard(task_id)
        name = spec.get("name") or spec.get("method", "task")
        if reply.get("error") is not None:
            metrics.tasks_finished.inc(tags={"outcome": "failed"})
            self.task_events.emit(task_id=task_id.hex(), name=name, state="FAILED",
                                  error=str(reply["error"])[:200])
            self._complete_task_error(spec, reply["error"])
            return
        metrics.tasks_finished.inc(tags={"outcome": "ok"})
        self.task_events.emit(task_id=task_id.hex(), name=name, state="FINISHED")
        for i, result in enumerate(reply["results"]):
            oid = ObjectID.for_task_return(task_id, i)
            entry = self.memory_store.get(oid)
            if entry is None:
                continue
            if result.get("inline") is not None:
                entry.packed = result["inline"]
            else:
                entry.in_shm = True
                # completion-time location priming: the reply names the
                # sealing node, so get() goes straight to the pull with a
                # holder hint — zero directory round-trips in steady state
                node = result.get("node")
                if node is not None:
                    self._obj_locations.setdefault(oid, set()).add(node)
            entry.ready.set()

    def _complete_task_error(self, spec, error):
        self._inflight_pins.pop(spec["task_id"], None)
        if not isinstance(error, TaskCancelledError):
            self._cancelled_tasks.discard(spec["task_id"])
        if not isinstance(error, Exception):
            error = TaskError(str(error))
        if spec["num_returns"] == "streaming":
            state = self._gen_states.get(spec["task_id"])
            if state is not None and not state.done:
                state.error = error
                state.done = True
                state.event.set()
            return
        for i in range(spec["num_returns"]):
            oid = ObjectID.for_task_return(spec["task_id"], i)
            entry = self.memory_store.get(oid)
            if entry is not None:
                entry.error = error
                entry.ready.set()

    # -------------------------------------------------- streaming generators
    async def rpc_generator_item(self, conn, p):
        """Executor reports one yielded item (ref: core_worker.proto:498
        ReportGeneratorItemReturns); the awaited ack is the backpressure
        (generator_waiter.h role: producer can't run far ahead)."""
        task_id = p["task_id"]
        state = self._gen_states.get(task_id)
        if state is None:
            return {"ok": False, "cancelled": True}  # consumer gone: stop
        if p.get("item") is not None:
            item = p["item"]
            oid = ObjectID.for_task_return(task_id, p["index"])
            entry = _MemEntry()
            if item.get("inline") is not None:
                entry.packed = item["inline"]
            else:
                entry.in_shm = True
                node = item.get("node")
                if node is not None:
                    self._obj_locations.setdefault(oid, set()).add(node)
            entry.ready.set()
            self.memory_store[oid] = entry
            state.items.append(self._new_owned_ref(oid))
        if p.get("done"):
            state.done = True
            if p.get("error") is not None:
                state.error = p["error"]
        state.event.set()
        return {"ok": True}

    async def gen_next(self, task_id: TaskID, timeout: float | None = None):
        """Next item ref, or None when the stream ends (async side)."""
        state = self._gen_states.get(task_id)
        if state is None:
            return None
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            if state.items:
                return state.items.pop(0)
            if state.error is not None:
                err = state.error
                raise err if isinstance(err, Exception) else TaskError(str(err))
            if state.done:
                return None
            state.event.clear()
            try:
                remain = (deadline - time.monotonic()) if deadline else None
                if remain is not None and remain <= 0:
                    raise GetTimeoutError(f"generator {task_id} timed out")
                await asyncio.wait_for(state.event.wait(), remain)
            except asyncio.TimeoutError:
                raise GetTimeoutError(f"generator {task_id} timed out") from None

    def gen_next_sync(self, task_id: TaskID, timeout: float | None = None):
        return self._run_sync(self.gen_next(task_id, timeout))

    def gen_completed(self, task_id: TaskID) -> bool:
        state = self._gen_states.get(task_id)
        return state is None or (state.done and not state.items)

    def gen_release(self, task_id: TaskID):
        self._gen_states.pop(task_id, None)

    async def _on_worker_lost(self, key, state, w, spec):
        """Retry on worker death (ref: task_manager.h retries). Streaming
        tasks don't replay: already-consumed items can't be un-delivered,
        so the stream fails fast instead."""
        if w in state.workers:
            state.workers.remove(w)
        if w.fast_lane is not None:
            self._fast_break_lane(w.fast_lane)
        self._task_worker.pop(spec["task_id"], None)
        if spec["task_id"] in self._cancelled_tasks:
            self._complete_task_error(
                spec, TaskCancelledError(str(spec["task_id"]))
            )
            state.inflight_tasks -= 1
            await self._pump(key, state)
            return
        if spec["num_returns"] == "streaming":
            self._complete_task_error(spec, WorkerCrashedError())
            state.inflight_tasks -= 1
            await self._pump(key, state)
            return
        spec["max_retries"] = spec.get("max_retries", 0) - 1
        if spec["max_retries"] >= 0:
            await state.pending.put(spec)
        else:
            self._complete_task_error(spec, WorkerCrashedError())
            state.inflight_tasks -= 1
        await self._pump(key, state)

    async def _maybe_return_lease(self, key, state: _SchedulingKeyState, w: _LeasedWorker):
        await asyncio.sleep(self.cfg.worker_lease_timeout_s)
        if w.busy or w not in state.workers:
            return
        if w.fast_lane is not None and w.fast_lane.inflight:
            return  # fast tasks in flight; their drain re-arms the watcher
        if time.monotonic() - w.idle_since < self.cfg.worker_lease_timeout_s * 0.9:
            return
        if w.fast_lane is not None and not self._fast_try_retire_lane(
                w.fast_lane):
            return  # a submit raced the idle check: lane is live again
        state.workers.remove(w)
        try:
            if w.conn is not None:
                await w.conn.close()
            conn = (
                self.raylet
                if tuple(w.raylet_address) == tuple(self.raylet_address)
                else await rpc.connect(*w.raylet_address)
            )
            try:
                await conn.call("return_lease", {"lease_id": w.lease_id})
            finally:
                if conn is not self.raylet:
                    await conn.close()
        except (rpc.RpcError, OSError):
            pass  # raylet died: the lease is already gone with it

    # ------------------------------------------------------------- actors
    def _resolve_runtime_env(self, env):
        """Per-call envs with raw paths get packaged (and uploaded,
        synchronously — the task must not race its own package to the
        worker); already-packaged descriptors and the init() default pass
        through."""
        if env is None:
            return self.default_runtime_env
        import re as _re

        def is_digest(v):
            return isinstance(v, str) and _re.fullmatch(r"[0-9a-f]{40}", v)

        wd = env.get("working_dir")
        mods = env.get("py_modules", ())
        # a non-digest entry must be a real directory: catch typos at
        # submission, not as a cryptic package-missing error on the worker
        for entry in ([wd] if wd else []) + list(mods):
            if not is_digest(entry) and not os.path.isdir(entry):
                raise ValueError(
                    f"runtime_env path {entry!r} is not a directory"
                )
        from ray_tpu import runtime_env as _renv

        needs_packaging = (
            (wd and os.path.isdir(wd))
            or any(os.path.isdir(p) for p in mods)
            # plugin fields (pip/uv/...) normalize driver-side: the worker
            # only ever sees packaged descriptors
            or any(env.get(name) is not None
                   and not (isinstance(env[name], dict)
                            and "digest" in env[name])
                   for name in _renv._PLUGINS)
        )
        if not needs_packaging:
            return env
        if _in_loop(self.loop):
            raise RuntimeError(
                "per-call runtime_env with directory paths cannot be "
                "packaged from the event-loop thread; package it at "
                "init(runtime_env=...) instead"
            )
        from ray_tpu.runtime_env import package_runtime_env

        def kv_put(key, blob):
            self._run_sync(self.gcs.call(
                "kv_put",
                {"ns": "runtime_env_packages", "key": key, "value": blob,
                 "overwrite": False},
            ))

        return package_runtime_env(env, kv_put)

    def _build_actor_spec(self, cls, args, kwargs, *, num_cpus=1.0, resources=None,
                          name=None, max_restarts=0, max_concurrency=1,
                          placement_group=None, bundle_index=-1,
                          get_if_exists=False, lifetime=None,
                          runtime_env=None, concurrency_groups=None,
                          scheduling_strategy=None) -> dict:
        res = dict(resources or {})
        res.setdefault("CPU", num_cpus)
        # per-method concurrency groups (ref: concurrency_group_manager.cc):
        # methods annotated with @ray_tpu.method(concurrency_group=...) map
        # onto named executor pools sized by `concurrency_groups`
        method_groups = {}
        method_num_returns = {}
        for mname in dir(cls):  # dir() walks the MRO: inherited methods count
            m = getattr(cls, mname, None)
            opts = getattr(m, "__rt_method_opts__", None)
            if not callable(m) or not opts:
                continue
            if opts.get("concurrency_group"):
                method_groups[mname] = opts["concurrency_group"]
            if opts.get("num_returns"):
                method_num_returns[mname] = opts["num_returns"]
        declared = set(concurrency_groups or {})
        undeclared = set(method_groups.values()) - declared
        if undeclared:
            raise ValueError(
                f"methods reference undeclared concurrency groups "
                f"{sorted(undeclared)}; declare them in "
                f"@remote(concurrency_groups={{...}})"
            )
        return {
            "runtime_env": self._resolve_runtime_env(runtime_env),
            "actor_id": ActorID.generate(),
            "name": name,
            "class_blob": serialization.ship_dumps(cls),
            "args": args,
            "kwargs": kwargs,
            "resources": res,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "concurrency_groups": dict(concurrency_groups or {}),
            "method_groups": method_groups,
            "method_num_returns": method_num_returns,
            "placement_group": placement_group,
            "bundle_index": bundle_index,
            "owner_address": self.address,
            "get_if_exists": get_if_exists,
            "lifetime": lifetime,
            "scheduling_strategy": scheduling_strategy,
        }

    async def _register_actor(self, spec: dict) -> dict:
        spec["args"] = await self._resolve_args(spec["args"])
        spec["kwargs"] = dict(
            zip(
                spec["kwargs"].keys(),
                await self._resolve_args(list(spec["kwargs"].values())),
            )
        )
        view = await self.gcs.call("register_actor", {"spec": spec})
        self._actor_info[view["actor_id"]] = view
        return view

    def _seed_autokill(self, spec: dict) -> None:
        """Enroll a to-be-created actor in handle refcounting BEFORE its
        first handle exists (ActorHandle.__init__ only counts enrolled
        ids). Named and detached actors are reachable/alive beyond the
        creating handle, so they never enroll."""
        if spec["name"] is None and spec.get("lifetime") != "detached":
            with self._rc_lock:
                self._actor_handle_counts.setdefault(spec["actor_id"], 0)

    def note_actor_handle_created(self, actor_id: ActorID) -> bool:
        """ActorHandle.__init__ hook: count an owner-local handle.
        Returns whether this handle participates in autokill accounting
        (enrolled unnamed actors only; lookups of named/foreign actors
        return False)."""
        with self._rc_lock:
            if self._closed or actor_id not in self._actor_handle_counts:
                return False
            self._actor_handle_counts[actor_id] += 1
            return True

    def note_actor_handle_shipped(self, actor_id: ActorID) -> None:
        """ActorHandle.__reduce__ hook: a serialized handle may be alive
        anywhere — permanently exempt the actor from autokill."""
        with self._rc_lock:
            self._actor_no_autokill.add(actor_id)

    def note_actor_handle_dropped(self, actor_id: ActorID) -> None:
        """ActorHandle.__del__ hook: when the LAST owner-local handle of
        an enrolled actor drops, schedule a drain-gated kill so the
        actor's lease flows back to the raylet."""
        with self._rc_lock:
            n = self._actor_handle_counts.get(actor_id)
            if n is None:
                return
            self._actor_handle_counts[actor_id] = n = n - 1
            if (n > 0 or self._closed
                    or actor_id in self._actor_no_autokill):
                return
        try:
            asyncio.run_coroutine_threadsafe(
                self._autokill_actor(actor_id), self.loop)
        except RuntimeError:
            # loop already closed (interpreter exit): the GCS owner-death
            # reap returns the lease instead
            pass

    async def _autokill_actor(self, actor_id: ActorID) -> None:
        """Kill an unreferenced unnamed actor once its submitted work
        drains (queued RPC specs, in-flight RPC calls, fast-lane ring
        traffic) — never yanks a worker out from under a live call. The
        wait is bounded: a wedged actor is left to the normal death
        paths rather than pinning this coroutine forever."""
        deadline = self.loop.time() + 30.0
        while self.loop.time() < deadline:
            lane = self._fast_actor_lanes.get(actor_id)
            if (not self._actor_queues.get(actor_id)
                    and not self._actor_inflight.get(actor_id)
                    and not (lane is not None and lane.inflight)):
                break
            await asyncio.sleep(0.05)
        with self._rc_lock:
            if (self._closed
                    or self._actor_handle_counts.get(actor_id, 0) > 0
                    or actor_id in self._actor_no_autokill):
                return
            self._actor_handle_counts.pop(actor_id, None)
        try:
            await self.gcs.call("kill_actor", {"actor_id": actor_id,
                                               "no_restart": True})
        except Exception:
            log.debug("autokill of actor %s failed", actor_id.hex(),
                      exc_info=True)

    def create_actor(self, cls, args, kwargs, **opts) -> ActorHandle:
        spec = self._build_actor_spec(cls, args, kwargs, **opts)
        self._seed_autokill(spec)
        if _in_loop(self.loop):
            # Called from the event loop (e.g. an async actor creating other
            # actors): can't block. The actor_id is chosen client-side, so
            # the handle is valid immediately; registration completes in the
            # background and callers wait for ALIVE via _actor_connection.
            if spec["get_if_exists"]:
                raise RuntimeError(
                    "get_if_exists=True requires the registration reply and "
                    "cannot be used from the event-loop thread; await "
                    "create_actor_async instead"
                )
            self._bg.spawn(self._register_actor(spec), self.loop)
            return ActorHandle(spec["actor_id"], core=self,
                               options=_handle_options(spec))
        view = self._run_sync(self._register_actor(spec))
        return ActorHandle(view["actor_id"], core=self,
                           options=_handle_options(spec))

    async def create_actor_async(self, cls, args, kwargs, **opts) -> ActorHandle:
        """Event-loop-safe actor creation (supports get_if_exists)."""
        spec = self._build_actor_spec(cls, args, kwargs, **opts)
        self._seed_autokill(spec)
        view = await self._register_actor(spec)
        return ActorHandle(view["actor_id"], core=self,
                           options=_handle_options(spec))

    async def get_actor_by_name_async(self, name: str) -> ActorHandle | None:
        info = await self.gcs.call("get_actor", {"name": name})
        if info is None or info.get("state") == DEAD:
            return None
        self._actor_info[info["actor_id"]] = info
        return ActorHandle(info["actor_id"], core=self,
                           options=_handle_options(info))

    def submit_actor_task(self, handle: ActorHandle, method: str, args, kwargs,
                          num_returns=1,
                          concurrency_group: str | None = None,
                          _tmpl: ActorCallTemplate | None = None,
                          unordered: bool = False
                          ) -> ObjectRef | list[ObjectRef]:
        """Submission order is fixed here (sync, caller thread); a per-actor
        pump coroutine then resolves deps, assigns per-connection sequence
        numbers and pipelines pushes — the reference's ActorTaskSubmitter
        shape (ref: actor_task_submitter.h:75, ordered sends + out-of-order
        replies). ``_tmpl`` (set by ref.ActorMethod.remote) carries the
        frozen per-(handle, method) submission state so the fast try skips
        every per-call re-derivation."""
        if _tmpl is not None:
            if _tmpl.opts_ok:
                ref = self._try_fast_actor_submit(handle.actor_id, method,
                                                  args, kwargs, _tmpl)
                if ref is not None:
                    return ref
        elif (num_returns == 1 and concurrency_group is None
                and not self.cfg.tracing_enabled):
            ref = self._try_fast_actor_submit(handle.actor_id, method,
                                              args, kwargs)
            if ref is not None:
                return ref
        task_id = TaskID.generate_actor()
        actor_id = handle.actor_id
        metrics.actor_calls.inc()
        self.task_events.emit(task_id=task_id.hex(), name=method,
                              state="PENDING_ARGS_AVAIL", actor_id=actor_id.hex())
        streaming = num_returns == "streaming"
        refs = []
        if streaming:
            self._gen_states[task_id] = _GenState()
        else:
            for i in range(num_returns):
                roid = ObjectID.for_task_return(task_id, i)
                self.memory_store[roid] = _MemEntry()
                refs.append(self._new_owned_ref(roid))
        spec = {
            "task_id": task_id,
            "actor_id": actor_id,
            "method": method,
            "args": args,
            "kwargs": kwargs,
            "num_returns": num_returns,
            "owner_address": self.address,
            "seq": None,
            "concurrency_group": concurrency_group,
        }
        if unordered:
            # independent logical call (serve router fallback): skips the
            # fast->RPC drain barrier in _prepare_actor_task, so it never
            # parks behind the lane's in-flight ring traffic
            spec["unordered"] = True
        if self.cfg.tracing_enabled:
            self._emit_submit_span(spec, method)
        q = self._actor_queues.setdefault(actor_id, [])
        q.append(spec)
        self._call_on_loop(self._ensure_actor_pump(actor_id))
        if streaming:
            return ObjectRefGenerator(task_id, self)
        return refs[0] if num_returns == 1 else refs

    async def _ensure_actor_pump(self, actor_id: ActorID):
        """Single pump per actor owns BOTH dispatch and reconnect recovery,
        so replayed in-flight specs always precede anything newer — no
        separate recovery task can race the send order."""
        if actor_id in self._actor_pump_running:
            return
        self._actor_pump_running.add(actor_id)
        try:
            q = self._actor_queues.setdefault(actor_id, [])
            while True:
                dead = self._actor_recover_pending.get(actor_id)
                if dead:
                    conn = next(iter(dead))
                    dead.discard(conn)
                    await self._recover_actor_conn(actor_id, conn)
                    continue  # replay was prepended; loop re-checks
                if not q:
                    return
                # collect a same-connection batch: each spec keeps its own
                # seq + reply future (scatter push), so FIFO and per-call
                # completion are unchanged — only the frames coalesce
                batch: list = []
                bconn = None
                recover = False
                while q and len(batch) < self.cfg.push_batch_size:
                    spec = q[0]
                    try:
                        conn = await self._prepare_actor_task(spec)
                    except _RecoveryNeeded:
                        recover = True
                        break  # spec stays queued; replay goes out first
                    except Exception as e:
                        q.pop(0)
                        self._complete_task_error(spec, e)
                        continue
                    q.pop(0)
                    if bconn is not None and conn is not bconn:
                        # connection changed mid-collect (reconnect): flush
                        # what we have, start a new batch on the new conn
                        self._send_actor_batch(bconn, batch)
                        batch = []
                    bconn = conn
                    batch.append(spec)
                if batch:
                    self._send_actor_batch(bconn, batch)
                if recover:
                    continue
        finally:
            self._actor_pump_running.discard(actor_id)

    async def _prepare_actor_task(self, spec):
        """Resolve deps, pick the connection, assign the per-connection
        sequence number and register the spec for reconnect replay. Raises
        _RecoveryNeeded (before any seq is taken) when a replay must go out
        first."""
        if not spec.get("_resolved"):  # replayed specs are already done
            pins: list = []
            spec["args"] = await self._resolve_args(spec["args"], pins)
            spec["kwargs"] = dict(
                zip(spec["kwargs"].keys(),
                    await self._resolve_args(list(spec["kwargs"].values()), pins))
            )
            spec["_resolved"] = True
            if pins:
                self._inflight_pins[spec["task_id"]] = pins
        # per-caller FIFO across the fast->RPC per-call fallback: ring
        # records already in flight must complete before any RPC call
        # dispatches. Event-driven: the reply thread sets drain_evt when
        # the lane's inflight map empties (and break-lane does too), with
        # a bounded re-check instead of the old 1ms constant-sleep poll
        # (the RT013 shape).
        lane = self._fast_actor_lanes.get(spec["actor_id"])
        if spec.get("unordered"):
            lane = None  # independent call: no FIFO barrier against the ring
        if lane is not None and lane.inflight and not lane.broken:
            evt = lane.drain_evt
            lane.drain_waiters += 1  # reply threads signal only when > 0
            try:
                while lane.inflight and not lane.broken:
                    if evt is None:  # no event (not expected): bounded poll
                        await asyncio.sleep(0.01)
                        continue
                    evt.clear()
                    if not lane.inflight or lane.broken:
                        break  # emptied between the check and the clear
                    try:
                        await asyncio.wait_for(evt.wait(), timeout=0.25)
                    except asyncio.TimeoutError:
                        pass  # defensive re-check; the set may have raced
            finally:
                lane.drain_waiters -= 1
        conn = await self._actor_connection(spec["actor_id"])
        if self._actor_recover_pending.get(spec["actor_id"]):
            # a connection died while this dispatch was suspended: the
            # replay must go out first — hand the spec back to the pump
            raise _RecoveryNeeded()
        seq = self._conn_seq.get(conn, 0)
        self._conn_seq[conn] = seq + 1
        spec["seq"] = seq
        self._actor_inflight.setdefault(spec["actor_id"], {})[spec["task_id"]] = spec
        return conn

    def _send_actor_batch(self, conn, specs: list):
        # pipelined: don't await replies here, keep the pump moving
        if len(specs) == 1:
            self._bg.spawn(self._await_actor_reply(conn, specs[0]), self.loop)
            return
        futs = conn.call_scatter(
            "push_actor_task_multi", [{"spec": s} for s in specs])
        for spec, fut in zip(specs, futs):
            self._bg.spawn(self._await_actor_reply(conn, spec, fut), self.loop)

    async def _await_actor_reply(self, conn, spec, fut=None):
        try:
            if fut is None:
                reply = await conn.call("push_actor_task", {"spec": spec})
            else:
                reply = await fut
            self._actor_inflight.get(spec["actor_id"], {}).pop(spec["task_id"], None)
            self._apply_task_reply(spec, reply)
        except rpc.ConnectionLost:
            # mark the conn for pump-owned recovery and wake the pump; the
            # spec stays in _actor_inflight for the replay
            aid = spec["actor_id"]
            self._actor_recover_pending.setdefault(aid, set()).add(conn)
            self._bg.spawn(self._ensure_actor_pump(aid), self.loop)
        except Exception as e:
            self._actor_inflight.get(spec["actor_id"], {}).pop(spec["task_id"], None)
            self._complete_task_error(spec, e)

    async def _recover_actor_conn(self, actor_id: ActorID, conn):
        """Runs INSIDE the actor's pump: requeue the dead connection's
        in-flight specs at the queue head in original send order, so FIFO
        holds across the reconnect (ref: actor_task_submitter sequence
        replay). Execution is at-least-once across reconnects, same as
        worker-crash retries. Any failure here fails the replayed specs —
        they are never silently dropped."""
        if self._actor_conns.get(actor_id) is conn:
            self._actor_conns.pop(actor_id, None)
        self._conn_seq.pop(conn, None)
        inflight = self._actor_inflight.get(actor_id, {})
        replay = list(inflight.values())  # dict preserves send order
        inflight.clear()
        if not replay:
            return
        info = None
        for i in range(3):  # ride out a transient GCS blip
            try:
                info = await self._refresh_actor(actor_id)
                break
            except Exception:
                # exponential backoff: a GCS mid-failover gets room to
                # come back instead of three probes in 600ms (RT013)
                await asyncio.sleep(0.1 * (1 << i) * (0.5 + random.random()))
        alive = info and info.get("state") in (
            ALIVE, "RESTARTING", "PENDING_CREATION"
        )
        requeue = []
        for spec in replay:
            if spec["num_returns"] == "streaming":
                # never replay a generator: already-consumed items would
                # duplicate into the live stream
                self._complete_task_error(
                    spec, ActorError("actor connection lost mid-stream")
                )
            elif alive:
                spec["seq"] = None  # fresh seq on the new connection
                requeue.append(spec)
            else:
                cause = (info or {}).get("death_cause") or "actor connection lost"
                self._complete_task_error(spec, ActorError(cause))
        if requeue:
            q = self._actor_queues.setdefault(actor_id, [])
            q[:0] = requeue  # ahead of anything not yet sent

    async def _actor_connection(self, actor_id: ActorID) -> rpc.Connection:
        lock = self._actor_conn_locks.setdefault(actor_id, asyncio.Lock())
        async with lock:
            return await self._actor_connection_locked(actor_id)

    async def _actor_connection_locked(self, actor_id: ActorID) -> rpc.Connection:
        conn = self._actor_conns.get(actor_id)
        if conn is not None and not conn._closed:
            return conn
        info = self._actor_info.get(actor_id)
        deadline = time.monotonic() + self.cfg.worker_start_timeout_s
        stale_hits = 0
        while True:
            while True:
                if info is not None:
                    if info.get("state") == DEAD:
                        raise ActorError(info.get("death_cause") or "actor is dead")
                    if info.get("state") == ALIVE and info.get("address"):
                        break
                if time.monotonic() > deadline:
                    raise ActorError(f"actor {actor_id} not available in time")
                if actor_id not in self._subscribed_actors:
                    self._subscribed_actors.add(actor_id)
                    await self.gcs.call("subscribe", {"channel": f"actor:{actor_id.hex()}"})
                info = await self._refresh_actor(actor_id)
                if not (info and info.get("state") == ALIVE and info.get("address")):
                    await asyncio.sleep(0.05)
                    info = self._actor_info.get(actor_id)
            try:
                conn = await rpc.connect(*info["address"], timeout=1.0)
                break
            except rpc.ConnectionLost:
                # GCS can briefly advertise ALIVE at the old address after a
                # hard crash (reaper period lag); treat as stale and keep
                # waiting for the restarted actor to publish a reachable
                # address.
                if time.monotonic() > deadline:
                    raise ActorError(f"actor {actor_id} not reachable in time")
                # backoff: the restarted actor needs GCS registration +
                # bind time, and every caller of this actor retries here
                stale_hits += 1
                await asyncio.sleep(min(1.0, 0.1 * (2 ** (stale_hits - 1)))
                                    * (0.5 + random.random()))
                self._actor_info.pop(actor_id, None)
                info = None
        self._actor_conns[actor_id] = conn
        if actor_id not in self._subscribed_actors:
            # death subscription for every actor we talk to: the wait loop
            # above only subscribes when the first info lookup missed, but
            # fast eviction (actor-death listeners) needs the DEAD push
            # even for actors that resolved ALIVE immediately
            self._subscribed_actors.add(actor_id)
            try:
                await self.gcs.call(
                    "subscribe", {"channel": f"actor:{actor_id.hex()}"})
            except (rpc.RpcError, OSError):
                self._subscribed_actors.discard(actor_id)  # retry next connect
        if self.cfg.fastpath_enabled and self.store is not None:
            self._bg.spawn(self._fast_actor_attach(actor_id, conn), self.loop)
            if self._tunnel_ok():
                # remote actor (or tunnel_force): bind a tunnel lane —
                # the attach itself checks node identity and no-ops for
                # same-node actors, whose shm ring lane wins
                self._bg.spawn(self._tunnel_actor_attach(actor_id, conn),
                               self.loop)
        return conn

    async def _refresh_actor(self, actor_id: ActorID):
        info = await self.gcs.call("get_actor", {"actor_id": actor_id})
        if info is not None:
            self._actor_info[actor_id] = info
        return info

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        """Cancel a task (ref: ray.cancel, core_worker CancelTask):
        best-effort — the caller's pending refs fail with
        TaskCancelledError immediately (even if the task is dependency-
        blocked), a queued task never dispatches, and with force=True an
        executing task's worker is killed."""
        task_id = ref.id.task_id()
        if task_id.is_actor_task():
            # matches the documented contract (api.cancel): actor tasks run
            # to completion; half-cancelling the caller's ref would discard
            # a result whose side effects still happen.
            raise ValueError("actor tasks cannot be cancelled")
        self._cancelled_tasks.add(task_id)
        self._run_sync(self._cancel_async(task_id, force))

    def _fail_task_returns_cancelled(self, task_id: TaskID):
        i = 0
        while True:  # returns are dense indices; stop at the first miss
            oid = ObjectID.for_task_return(task_id, i)
            entry = self.memory_store.get(oid)
            if entry is None:
                break
            if entry.error is None and not entry.ready.is_set():
                entry.error = TaskCancelledError(str(task_id))
                entry.ready.set()
            i += 1

    async def _cancel_async(self, task_id: TaskID, force: bool):
        # the caller must not hang on a dep-blocked or in-flight task:
        # fail its return entries now (best-effort semantics — a task that
        # still completes keeps its stored result, but gets raise the
        # cancellation)
        self._fail_task_returns_cancelled(task_id)
        # drain it from any pending queue
        for state in self.sched_keys.values():
            kept = []
            while not state.pending.empty():
                spec = state.pending.get_nowait()
                if spec["task_id"] == task_id:
                    self._complete_task_error(
                        spec, TaskCancelledError(str(task_id))
                    )
                    state.inflight_tasks -= 1
                else:
                    kept.append(spec)
            for spec in kept:
                await state.pending.put(spec)
        if force:
            loc = self._task_worker.get(task_id)
            if loc is not None:
                raylet_addr, worker_id, wconn = loc
                # Ask the worker itself to die only if it is STILL running
                # this task — the identity check happens inside the worker
                # process, so a task that completed and a reused worker can
                # never be killed by a stale cancel.
                try:
                    killed = await wconn.call(
                        "cancel_if_current", {"task_id": task_id}, timeout=5)
                    if killed or self._task_worker.get(task_id) != loc:
                        return
                    # worker said "not mine" but the task is still mapped
                    # here: the push may be racing startup — retry once
                    # before escalating to a raylet kill
                    await asyncio.sleep(0.1)
                    killed = await wconn.call(
                        "cancel_if_current", {"task_id": task_id}, timeout=5)
                    if killed or self._task_worker.get(task_id) != loc:
                        return
                except Exception:
                    # worker loop unresponsive/conn dead: raylet fallback
                    log.debug("worker-side cancel failed", exc_info=True)
                # Fallback (worker wedged): kill via raylet, but only if the
                # task is still mapped to that same worker.
                if self._task_worker.get(task_id) != loc:
                    return
                try:
                    conn = (self.raylet
                            if tuple(raylet_addr) == tuple(self.raylet_address)
                            else await rpc.connect(*raylet_addr, timeout=5))
                    try:
                        if self._task_worker.get(task_id) == loc:
                            await conn.call("kill_worker", {"worker_id": worker_id})
                    finally:
                        if conn is not self.raylet:
                            await conn.close()
                except Exception:
                    log.debug("raylet-side cancel kill failed", exc_info=True)

    def kill_actor(self, actor_id: ActorID, no_restart=True):
        self._run_sync(self.gcs.call("kill_actor", {"actor_id": actor_id,
                                                    "no_restart": no_restart}))

    def get_actor_by_name(self, name: str) -> ActorHandle | None:
        info = self._run_sync(self.gcs.call("get_actor", {"name": name}))
        if info is None or info.get("state") == DEAD:
            return None
        self._actor_info[info["actor_id"]] = info
        return ActorHandle(info["actor_id"], core=self,
                           options=_handle_options(info))

    # ------------------------------------------------------ compiled DAGs
    def start_dag_loop(self, handle: ActorHandle, schedule: dict):
        """Kick off an actor's compiled-DAG loop; the RPC reply arrives when
        the loop exits at teardown (ref: compiled_dag_node.py actor loops).
        Returns a concurrent.futures.Future with the loop's summary."""

        async def go():
            conn = await self._actor_connection(handle.actor_id)
            reply = await conn.call("start_dag_loop", {"schedule": schedule},
                                    timeout=None)
            if isinstance(reply, dict) and reply.get("error") is not None:
                raise reply["error"]
            return reply.get("result") if isinstance(reply, dict) else reply

        return asyncio.run_coroutine_threadsafe(go(), self.loop)

    def wait_dag_loop(self, fut, timeout: float | None = None):
        return fut.result(timeout)

    # ------------------------------------------------------------ helpers
    def _store_executor(self):
        """Small private pool for blocking shm-store reads issued FROM the
        core loop. Never the loop's default executor: user code blocks
        api.get calls on that shared pool, and a store read queued behind
        a full set of blocked gets deadlocks the process."""
        ex = self._store_exec
        if ex is None:
            from concurrent.futures import ThreadPoolExecutor

            ex = self._store_exec = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="rt-store-get")
        return ex

    def _run_sync(self, coro, timeout=None):
        if _in_loop(self.loop):
            raise RuntimeError("sync call from loop thread")
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout)

    async def close(self):
        await self.task_events.flush()
        self._closed = True
        if self._store_exec is not None:
            self._store_exec.shutdown(wait=False)
            self._store_exec = None
        with self._fast_flush_cv:  # release the flusher backstop thread
            self._fast_flush_cv.notify_all()
        for lane in list(self._fast_lanes):
            # wake pump+sweeper (the sweeper owns the unmap); unlink the
            # name NOW so daemon threads killed at exit can't leak /dev/shm
            lane.broken = True
            lane.resume_evt.set()
            lane.ring.close(0)
            lane.ring.close(1)
            lane.ring.unlink()
        await self._bg.cancel_all()
        if self._tunnels is not None:
            try:
                await self._tunnels.close()
            except Exception:
                log.debug("tunnel close failed", exc_info=True)
        # return all leases
        for key, state in self.sched_keys.items():
            for w in state.workers:
                try:
                    if w.conn:
                        await w.conn.close()
                    conn = await rpc.connect(*w.raylet_address, timeout=2)
                    await conn.call("return_lease", {"lease_id": w.lease_id})
                    await conn.close()
                except (rpc.RpcError, OSError):
                    pass  # node already down: nothing to return
        for conn in self._actor_conns.values():
            await conn.close()
        for conn in self._owner_conns.values():
            try:
                await conn.close()
            except (rpc.RpcError, OSError):
                pass  # already dead: close is best-effort
        await self.server.stop()
        if self.gcs:
            await self.gcs.close()
        if self.raylet:
            await self.raylet.close()
        if self.store:
            self.store.close()


def _pack_bytes(meta, buffers, size) -> bytes:
    out = bytearray(size)
    serialization.pack_into(meta, buffers, memoryview(out))
    return bytes(out)


def _in_loop(loop) -> bool:
    try:
        return asyncio.get_running_loop() is loop
    except RuntimeError:
        return False


async def _wait_event(event: asyncio.Event, timeout: float | None):
    if timeout is None:
        await event.wait()
    else:
        try:
            await asyncio.wait_for(event.wait(), timeout)
        except asyncio.TimeoutError:
            pass

"""Multi-node-in-one-machine cluster harness.

Equivalent of the reference's `ray.cluster_utils.Cluster`
(ref: python/ray/cluster_utils.py:135): a real GCS plus N real raylets —
each with its own shm object store, resource ledger, and worker pool of
real subprocesses — so scheduling, spillback, object transfer and failure
paths are exercised without multiple machines. GCS and raylets run on one
background event loop; workers are real OS processes.
"""

from __future__ import annotations

import asyncio
import logging

from ray_tpu.core.gcs import GcsServer
from ray_tpu.core.raylet import Raylet
from ray_tpu.utils import rpc

_log = logging.getLogger(__name__)


class Cluster:
    def __init__(self, io: rpc.EventLoopThread | None = None, session: str | None = None):
        import os
        import time

        self._own_io = io is None
        self.io = io or rpc.EventLoopThread()
        self.session = session or f"c{os.getpid()}_{time.monotonic_ns() % 1_000_000}"
        self.gcs = GcsServer()
        self.gcs_address = self.io.run(self.gcs.start())
        self.raylets: list[Raylet] = []
        # crash-safe: unlink shm arenas even if the driver dies mid-test
        import atexit

        atexit.register(self._cleanup_stores)

    def _cleanup_stores(self):
        for raylet in self.raylets:
            try:
                raylet.store.destroy()
            except Exception:  # raylint: disable=RT012 — atexit hook: nowhere to report
                pass

    def add_node(
        self,
        num_cpus: float | None = None,
        resources: dict[str, float] | None = None,
        object_store_memory: int | None = None,
        labels: dict[str, str] | None = None,
    ) -> Raylet:
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        res.setdefault("CPU", 4.0)

        async def _add():
            raylet = Raylet(
                self.gcs_address,
                resources=res,
                store_capacity=object_store_memory,
                labels=labels,
                session=f"{self.session}_{len(self.raylets)}",
            )
            await raylet.start()
            return raylet

        raylet = self.io.run(_add())
        self.raylets.append(raylet)
        return raylet

    def remove_node(self, raylet: Raylet) -> None:
        """Gracefully stop a node (drains leases, says goodbye)."""
        self.raylets.remove(raylet)
        self.io.run(raylet.stop())

    def kill_node(self, raylet: Raylet) -> None:
        """Hard-kill a node (chaos testing; ref: test_utils.py:1419
        ResourceKiller): workers SIGKILLed, no lease returns, no GCS
        goodbye — failure is discovered, not announced."""
        self.raylets.remove(raylet)
        self.io.run(raylet.kill())

    def shutdown(self) -> None:
        for raylet in list(self.raylets):
            try:
                self.io.run(raylet.stop())
            except Exception:
                _log.debug("raylet stop failed", exc_info=True)
        self.raylets.clear()
        try:
            self.io.run(self.gcs.stop())
        except Exception:
            _log.debug("GCS stop failed", exc_info=True)
        if self._own_io:
            self.io.stop()

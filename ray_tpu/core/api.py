"""Public task/actor API: init, @remote, get/put/wait, actors, placement groups.

Equivalent of the reference's user-facing layer (ref: python/ray/_private/
worker.py init:1332 get:2757 put:2893 wait:2958 remote:3346,
remote_function.py:41, actor.py:708). The driver hosts its control-plane
sockets on a background event loop (EventLoopThread) and bridges the sync
API onto it.
"""

from __future__ import annotations

import atexit
import functools
import logging
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Iterable, Sequence

from ray_tpu.config import Config, get_config, set_config
from ray_tpu.core.core_client import CoreClient
from ray_tpu.core.ref import ActorHandle, ObjectRef
# NOTE: ray_tpu.util.scheduling_strategies is imported lazily inside the
# .remote() methods — ray_tpu.util's __init__ defines @remote actors and
# importing it here would recurse during package initialization
from ray_tpu.utils import rpc, serialization
from ray_tpu.utils.ids import PlacementGroupID

log = logging.getLogger(__name__)

_core: CoreClient | None = None
_io: rpc.EventLoopThread | None = None
_head_procs: list[subprocess.Popen] = []
_owned_cluster = None  # in-process Cluster when init() started one


def is_initialized() -> bool:
    return _core is not None


def get_core() -> CoreClient:
    if _core is None:
        init()
    return _core


def init(
    address: str | None = None,
    *,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    object_store_memory: int | None = None,
    runtime_env: dict | None = None,
    _in_process: bool = True,
    _client_mode: bool = False,
) -> None:
    """Bring up (or connect to) a cluster and attach this driver.

    Head mode (address=None) starts a GCS and one raylet. With
    ``_in_process=True`` (default) they run on the driver's background event
    loop — same wire protocol, no subprocess cost; with False they are real
    subprocesses like the reference's `ray start` topology
    (ref: _private/node.py:1479 start_ray_processes).
    """
    global _core, _io, _owned_cluster
    if _core is not None:
        return
    if address is None:
        # drivers launched by `job submit` auto-join their cluster
        # (ref: RAY_ADDRESS honored by ray.init)
        address = os.environ.get("RT_ADDRESS") or None
    cfg = get_config()
    if object_store_memory:
        cfg.object_store_memory = object_store_memory
        set_config(cfg)

    # deterministic fault injection (devtools/chaos): the driver — and
    # with it every in-process GCS/raylet — arms here; subprocess nodes
    # and workers arm in their own mains off the serialized config
    from ray_tpu.devtools import chaos

    chaos.maybe_arm()

    _io = rpc.EventLoopThread()

    if address is None:
        res = dict(resources or {})
        labels: dict[str, str] = {}
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        res.setdefault("CPU", float(os.cpu_count() or 1) * 4)
        if num_tpus is not None:
            res["TPU"] = float(num_tpus)
        else:
            # full topology autodetection: chips + generation marker +
            # slice name + pod-head resource + topology labels
            # (ref: _private/accelerators/tpu.py:24-61)
            from ray_tpu.accelerators.tpu import TPUAcceleratorManager

            for k, v in TPUAcceleratorManager.get_current_node_tpu_resources().items():
                res.setdefault(k, v)
            labels.update(TPUAcceleratorManager.get_current_node_tpu_labels())
        if _in_process:
            from ray_tpu.core.cluster import Cluster

            _owned_cluster = Cluster(io=_io)
            _owned_cluster.add_node(resources=res, labels=labels)
            gcs_addr = _owned_cluster.gcs_address
            raylet_addr = _owned_cluster.raylets[0].server.address
        else:
            gcs_addr, raylet_addr = _start_head_processes(res, labels)
    else:
        host, port = address.rsplit(":", 1)
        gcs_addr = (host, int(port))
        raylet_addr = _find_local_raylet(_io, gcs_addr)

    core = CoreClient(loop=_io.loop, client_mode=_client_mode)
    _io.run(core.connect(gcs_addr, raylet_addr), timeout=cfg.rpc_connect_timeout_s + 5)
    _core = core
    if runtime_env:
        core.default_runtime_env = _package_runtime_env(core, runtime_env)
    atexit.register(shutdown)


def _package_runtime_env(core: CoreClient, env: dict) -> dict:
    """Zip + upload runtime_env packages once (ref: working_dir.py
    upload_package_if_needed)."""
    from ray_tpu.runtime_env import package_runtime_env

    def kv_put(key: str, blob: bytes):
        core._run_sync(core.gcs.call(
            "kv_put",
            {"ns": "runtime_env_packages", "key": key, "value": blob,
             "overwrite": False},
        ))

    return package_runtime_env(env, kv_put)




def _start_head_processes(resources, labels=None) -> tuple[tuple[str, int], tuple[str, int]]:
    cfg = get_config()
    tmp = tempfile.mkdtemp(prefix="rt_head_")
    addr_file = os.path.join(tmp, "gcs_addr")
    env = dict(os.environ)
    env.update(cfg.to_env())
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
    gcs = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.core.gcs", "--address-file", addr_file], env=env
    )
    _head_procs.append(gcs)
    deadline = time.monotonic() + cfg.rpc_connect_timeout_s
    while not os.path.exists(addr_file):
        if time.monotonic() > deadline:
            raise TimeoutError("GCS did not start")
        time.sleep(0.05)
    with open(addr_file) as f:
        host, port = f.read().strip().rsplit(":", 1)
    gcs_addr = (host, int(port))
    res_arg = ",".join(f"{k}={v}" for k, v in resources.items() if k not in ("CPU", "TPU"))
    cmd = [
        sys.executable, "-m", "ray_tpu.core.raylet",
        "--gcs", f"{host}:{port}",
        "--num-cpus", str(resources.get("CPU", os.cpu_count() or 1)),
    ]
    if resources.get("TPU"):
        cmd += ["--num-tpus", str(resources["TPU"])]
    if res_arg:
        cmd += ["--resources", res_arg]
    if labels:
        cmd += ["--labels", ",".join(f"{k}={v}" for k, v in labels.items())]
    raylet = subprocess.Popen(cmd, env=env)
    _head_procs.append(raylet)
    raylet_addr = _find_local_raylet(_io, gcs_addr)
    return gcs_addr, raylet_addr


def _find_local_raylet(io: rpc.EventLoopThread, gcs_addr) -> tuple[str, int]:
    cfg = get_config()

    async def find():
        conn = await rpc.connect(*gcs_addr, timeout=cfg.rpc_connect_timeout_s)
        try:
            deadline = time.monotonic() + cfg.rpc_connect_timeout_s
            while time.monotonic() < deadline:
                cluster = await conn.call("get_cluster", {})
                if cluster:
                    return tuple(cluster[0]["address"])
                import asyncio

                await asyncio.sleep(0.05)
            raise TimeoutError("no raylet registered with the GCS")
        finally:
            await conn.close()

    return io.run(find())


def shutdown() -> None:
    global _core, _io, _owned_cluster
    if _core is not None and _io is not None:
        try:
            _io.run(_core.close(), timeout=10)
        except Exception:
            log.debug("core close failed during shutdown", exc_info=True)
    _core = None
    if _owned_cluster is not None:
        try:
            _owned_cluster.shutdown()
        except Exception:
            log.debug("cluster shutdown failed", exc_info=True)
        _owned_cluster = None
    for p in _head_procs:
        try:
            p.terminate()
        except OSError:
            pass
    for p in _head_procs:  # reap: no zombies, and raylets finish shm cleanup
        try:
            p.wait(timeout=5)
        except (subprocess.TimeoutExpired, OSError):
            try:
                p.kill()
                p.wait(timeout=2)
            except (subprocess.TimeoutExpired, OSError):
                pass  # unkillable child: the OS reaps it at exit
    _head_procs.clear()
    if _io is not None:
        _io.stop()
        _io = None


# ---------------------------------------------------------------- data plane
def put(value: Any) -> ObjectRef:
    return get_core().put_value(value)


def get(refs, timeout: float | None = None):
    core = get_core()
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get takes ObjectRefs, got {type(r)}")
    start = time.monotonic()
    # fast-path refs resolve straight off the shm reply rings, in this
    # thread, without a loop round-trip (see core/fastpath.py)
    fast = core.fast_prepass(ref_list, timeout)
    # completion fast lane: anything already local (ready memory-store
    # entries, sealed local shm results) resolves on this thread too —
    # the loop round-trip is only paid for genuinely remote/pending refs
    if len(fast) < len(ref_list):
        fast.update(core.get_local_prepass(
            [r for r in ref_list if r.id not in fast]))
    # promise refs (the serve router's retry-loop refs) resolve on this
    # thread off their threading.Event twin — but only when EVERY
    # pending ref is promise-backed: a mixed list must go through
    # get_async so promise waits and remote pulls overlap (a serial
    # prepass here would degrade mixed-list latency from max toward sum)
    pending = [r for r in ref_list if r.id not in fast]
    if pending and all(
            getattr(core.memory_store.get(r.id), "t_ready", None) is not None
            for r in pending):
        remaining = (None if timeout is None
                     else max(0.0, timeout - (time.monotonic() - start)))
        fast.update(core.promise_prepass(pending, remaining))
    slow_refs = ([r for r in ref_list if r.id not in fast]
                 if fast else ref_list)
    slow_values = []
    if slow_refs:
        remaining = (None if timeout is None
                     else max(0.0, timeout - (time.monotonic() - start)))
        slow_values = core._run_sync(
            core.get_async(slow_refs, remaining), timeout=None)
    if not fast:
        return slow_values[0] if single else slow_values
    it = iter(slow_values)
    values = []
    for r in ref_list:
        hit = fast.get(r.id)
        if hit is None:
            values.append(next(it))
        elif hit[0] == "v":
            values.append(serialization.unpack(hit[1]))
        elif hit[0] == "V":
            values.append(hit[1])
        else:
            raise hit[1]
    return values[0] if single else values


async def _async_get(ref: ObjectRef):
    import asyncio

    core = get_core()
    if _in_core_loop(core):
        values = await core.get_async([ref], None)
        return values[0]
    # foreign event loop (driver asyncio code, a user loop in a worker
    # thread): the core client's wait primitives are affine to the core
    # loop — run the get THERE and await the bridged future here, else
    # completion wakeups land on a loop that is not running this task
    # and the await never resolves
    fut = asyncio.run_coroutine_threadsafe(core.get_async([ref], None),
                                           core.loop)
    values = await asyncio.wrap_future(fut)
    return values[0]


def _in_core_loop(core) -> bool:
    import asyncio

    try:
        return asyncio.get_running_loop() is core.loop
    except RuntimeError:
        return False


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
    fetch_local: bool = True,
):
    core = get_core()
    refs = list(refs)
    if num_returns > len(refs):
        raise ValueError("num_returns > len(refs)")
    # completion fast lane: ready refs are counted on this thread, and a
    # shortfall made up purely of fast-lane in-flight refs waits on the
    # reply-stream condition variable (ring completions wake it) — the
    # loop path is only for refs it alone can resolve (borrowed, RPC)
    res = core.fast_wait_prepass(refs, num_returns, timeout)
    if res is not None:
        return res
    return core._run_sync(core.wait_async(refs, num_returns, timeout, fetch_local))


# ------------------------------------------------------------------- tasks
class SubmitTemplate:
    """Frozen per-handle submission state (ref: the SchedulingKey /
    lease-cache pairing in normal_task_submitter.h — the reference
    resolves a task's scheduling identity once and reuses it for every
    steady-state push).

    Everything a ``.remote()`` call used to re-derive per call — the
    resources dict, the normalized scheduling strategy, the placement
    target, the registered function id and the ring scheduling key — is
    resolved ONCE here, at the first ``.remote()`` of a handle.

    Invalidation story (each falls back to the slow RPC path, which stays
    the source of truth):
      * ``.options()`` fork → a NEW RemoteFunction → its own template;
      * runtime_env / core change → ``env_token``/``core`` mismatch on the
        next call rebuilds the template;
      * worker death mid-flight → the fast lane breaks and in-flight ring
        records replay over RPC (core_client._fast_break_lane); the
        template itself stays valid.
    """

    __slots__ = ("core", "env_token", "func_id", "resources", "sched_key",
                 "num_returns", "max_retries", "placement_group",
                 "bundle_index", "scheduling_node", "scheduling_strategy",
                 "name", "runtime_env", "fast_ok")


class RemoteFunction:
    """Handle produced by @remote on a function (ref: remote_function.py:41)."""

    def __init__(self, fn, **default_opts):
        self._fn = fn
        self._opts = default_opts
        self._tmpl: SubmitTemplate | None = None
        functools.update_wrapper(self, fn)

    def __getstate__(self):
        # the template pins the driver's CoreClient: never ship it with a
        # handle that travels to a worker (it rebuilds there on first use)
        state = self.__dict__.copy()
        state["_tmpl"] = None
        return state

    def options(self, **opts) -> "RemoteFunction":
        merged = {**self._opts, **opts}
        return RemoteFunction(self._fn, **merged)

    def remote(self, *args, **kwargs):
        core = get_core()
        tmpl = self._tmpl
        if (tmpl is None or tmpl.core is not core
                or tmpl.env_token is not core.default_runtime_env):
            tmpl = self._tmpl = self._build_template(core)
        return core.submit_template(tmpl, self._fn, args, kwargs)

    def _build_template(self, core) -> SubmitTemplate:
        o = self._opts
        resources = dict(o.get("resources") or {})
        resources["CPU"] = float(o.get("num_cpus", 1.0))
        if o.get("num_tpus"):
            resources["TPU"] = float(o["num_tpus"])
        from ray_tpu.util import scheduling_strategies

        pg = o.get("placement_group")
        strategy = o.get("scheduling_strategy")
        bundle_index = o.get("placement_group_bundle_index", -1)
        if isinstance(strategy, scheduling_strategies.
                      PlacementGroupSchedulingStrategy):
            pg = strategy.placement_group
            bundle_index = strategy.placement_group_bundle_index
        t = SubmitTemplate()
        t.core = core
        t.env_token = core.default_runtime_env
        t.resources = resources
        t.num_returns = o.get("num_returns", 1)
        t.max_retries = o.get("max_retries")
        t.placement_group = pg.id if isinstance(pg, PlacementGroup) else pg
        t.bundle_index = bundle_index
        t.scheduling_node = o.get("_scheduling_node")
        t.scheduling_strategy = scheduling_strategies.normalize(strategy)
        t.name = o.get("name")
        t.runtime_env = o.get("runtime_env")
        t.func_id = None
        t.sched_key = None
        # a custom max_retries does NOT disqualify the fast path: the
        # driver-side lineage tuple carries the budget, and break-lane
        # recovery resubmits with it (chaos kill schedules exposed the
        # earlier config-default reset)
        t.fast_ok = (
            t.num_returns == 1 and t.placement_group is None
            and t.scheduling_node is None and t.runtime_env is None
            and t.scheduling_strategy is None and t.name is None)
        if t.fast_ok:
            # register now (once per template) so steady-state calls skip
            # the per-call registration probe entirely
            t.func_id = core._register_function(self._fn)
            t.fast_ok = bool(getattr(self._fn, "__rt_fast_ok__", False))
            if t.fast_ok:
                t.sched_key = (t.func_id,
                               tuple(sorted(resources.items())),
                               None, -1, None, None)
        return t

    def __call__(self, *a, **k):
        raise TypeError(
            "remote functions cannot be called directly; use .remote() "
            "or call the original function"
        )


class ActorClass:
    """Handle produced by @remote on a class (ref: actor.py:708)."""

    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._opts = default_opts

    def options(self, **opts) -> "ActorClass":
        return ActorClass(self._cls, **{**self._opts, **opts})

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.util import scheduling_strategies

        o = self._opts
        pg = o.get("placement_group")
        strategy = o.get("scheduling_strategy")
        bundle_index = o.get("placement_group_bundle_index", -1)
        if isinstance(strategy, scheduling_strategies.
                      PlacementGroupSchedulingStrategy):
            pg = strategy.placement_group
            bundle_index = strategy.placement_group_bundle_index
        return get_core().create_actor(
            self._cls,
            args,
            kwargs,
            num_cpus=float(o.get("num_cpus", 1.0)),
            resources=_actor_resources(o),
            name=o.get("name"),
            max_restarts=int(o.get("max_restarts", 0)),
            max_concurrency=int(o.get("max_concurrency", 1)),
            placement_group=pg.id if isinstance(pg, PlacementGroup) else pg,
            bundle_index=bundle_index,
            get_if_exists=bool(o.get("get_if_exists", False)),
            lifetime=o.get("lifetime"),
            runtime_env=o.get("runtime_env"),
            concurrency_groups=o.get("concurrency_groups"),
            scheduling_strategy=scheduling_strategies.normalize(strategy),
        )


def method(*, concurrency_group: str | None = None,
           num_returns: int | None = None):
    """Annotate an actor method (ref: ray.method): assign it to a named
    concurrency group declared in @remote(concurrency_groups={...}) and/or
    fix its num_returns."""

    def deco(fn):
        fn.__rt_method_opts__ = {
            "concurrency_group": concurrency_group,
            "num_returns": num_returns,
        }
        return fn

    return deco


def _actor_resources(o: dict) -> dict:
    resources = dict(o.get("resources") or {})
    if o.get("num_tpus"):
        resources["TPU"] = float(o["num_tpus"])
    return resources


def remote(*args, **options):
    """@ray_tpu.remote decorator for functions and classes.

    ``in_specs``/``out_specs`` (PartitionSpecs) switch the handle onto
    the sharded object plane: one task per shard, routed to the node
    holding it, with collective-backed resharding on spec disagreement
    (see ray_tpu/sharded/submit.py)."""

    def wrap(obj):
        if "in_specs" in options or "out_specs" in options:
            if isinstance(obj, type):
                raise TypeError(
                    "in_specs/out_specs apply to functions; shard actor "
                    "inputs by passing ShardedObjectRefs to methods")
            from ray_tpu.sharded.submit import ShardedFunction

            return ShardedFunction(obj, options)
        if isinstance(obj, type):
            return ActorClass(obj, **options)
        return RemoteFunction(obj, **options)

    if len(args) == 1 and not options and callable(args[0]):
        return wrap(args[0])
    return wrap


# ---------------------------------------------------------- sharded plane
def put_sharded(value, **kw):
    """Store a sharded array as per-host shm shards behind ONE manifest
    (see ray_tpu/sharded/plane.py). Never materializes the global array."""
    from ray_tpu.sharded import plane

    return plane.put_sharded(value, **kw)


def get_sharded(sref, **kw):
    """Reassemble a device-local jax.Array from a ShardedObjectRef,
    zero-copy from local shm shards."""
    from ray_tpu.sharded import plane

    return plane.get_sharded(sref, **kw)


def reshard(sref, spec, **kw):
    """Redistribute a ShardedObjectRef to a new PartitionSpec through one
    XLA collective program (no driver gather-scatter)."""
    from ray_tpu.sharded.reshard import reshard as _reshard

    return _reshard(sref, spec, **kw)


class CppFunction:
    """Cross-language handle for a task implemented in a C++ worker binary
    (ref: cpp/ worker API + cross_language call surface). The function is
    resolved worker-side from the binary's RT_REMOTE registry by name."""

    def __init__(self, name: str, *, num_returns: int = 1,
                 resources: dict | None = None):
        self._name = name
        self._num_returns = num_returns
        self._resources = resources

    def options(self, *, num_returns: int | None = None,
                resources: dict | None = None) -> "CppFunction":
        return CppFunction(
            self._name,
            num_returns=self._num_returns if num_returns is None else num_returns,
            resources=self._resources if resources is None else resources,
        )

    def remote(self, *args):
        return get_core().submit_task(
            ("cpp", self._name), args, {},
            num_returns=self._num_returns,
            resources=self._resources,
            max_retries=0,  # native tasks: no automatic re-execution yet
        )


def cpp_function(name: str, **options) -> CppFunction:
    """Handle to a C++ task registered as ``name`` via RT_REMOTE in the
    cluster's C++ worker binary (configured with RT_CPP_WORKER)."""
    return CppFunction(name, **options)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Cancel a task (ref: ray.cancel): queued tasks complete with
    TaskCancelledError; with force=True an executing task's worker is
    killed. Actor tasks cannot be cancelled (matches the reference's
    default actor-task semantics)."""
    get_core().cancel_task(ref, force=force)


class RuntimeContext:
    """(ref: ray.runtime_context.RuntimeContext)"""

    def __init__(self, core):
        self._core = core

    @property
    def job_id(self):
        return self._core.job_id

    @property
    def node_id(self):
        return self._core.node_id

    @property
    def worker_id(self):
        return self._core.worker_id

    @property
    def gcs_address(self):
        return getattr(self._core, "gcs_address", None)

    def get_actor_id(self):
        from ray_tpu.core import worker as _worker_mod  # circular-safe

        w = getattr(_worker_mod, "_current_worker", None)
        return w.actor_id if w is not None else None

    def get(self) -> dict:
        return {
            "job_id": self.job_id,
            "node_id": self.node_id,
            "worker_id": self.worker_id,
            "actor_id": self.get_actor_id(),
            "gcs_address": self.gcs_address,
        }


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(get_core())


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    get_core().kill_actor(actor.actor_id, no_restart=no_restart)


def get_actor(name: str) -> ActorHandle:
    handle = get_core().get_actor_by_name(name)
    if handle is None:
        raise ValueError(f"no actor named {name!r}")
    return handle


# --------------------------------------------------------- placement groups
class PlacementGroup:
    """(ref: python/ray/util/placement_group.py:42)"""

    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict]):
        self.id = pg_id
        self.bundles = bundles

    def ready(self, timeout: float = 30.0) -> bool:
        """True once every bundle is committed. Observes the PG state
        machine (PENDING → CREATED → RESCHEDULING → REMOVED): PENDING
        and RESCHEDULING keep waiting — the GCS is creating or repairing
        the group after a node death — so a call issued mid-repair
        returns True when the repair commits rather than flapping False."""
        return get_core().wait_placement_group_ready(self.id, timeout)

    def state(self) -> dict | None:
        """Latest GCS view: ``{state, bundle_nodes, bundles, strategy,
        reschedule_cause, reschedules}`` — ``state`` is one of PENDING /
        CREATED / RESCHEDULING / REMOVED; ``reschedule_cause`` names the
        node loss behind the most recent repair."""
        return get_core().get_placement_group_state(self.id)

    @property
    def bundle_specs(self):
        return self.bundles


def placement_group(
    bundles: list[dict[str, float]], strategy: str = "PACK", name: str = ""
) -> PlacementGroup:
    core = get_core()
    pg_id = PlacementGroupID.generate()
    core._run_sync(
        core.gcs.call(
            "create_placement_group",
            {"pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name},
        )
    )
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    core = get_core()
    core._run_sync(core.gcs.call("remove_placement_group", {"pg_id": pg.id}))


# ------------------------------------------------------------------ cluster
def nodes() -> list[dict]:
    core = get_core()
    return core._run_sync(core.gcs.call("get_cluster", {}))


def cluster_resources() -> dict[str, float]:
    total: dict[str, float] = {}
    for n in nodes():
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> dict[str, float]:
    total: dict[str, float] = {}
    for n in nodes():
        for k, v in n["resources_available"].items():
            total[k] = total.get(k, 0.0) + v
    return total

"""Memory monitor + OOM worker killing.

TPU-native counterpart of the reference's memory protection (ref:
src/ray/common/memory_monitor.h:52 usage polling,
src/ray/raylet/worker_killing_policy.h:39 — kill the newest retriable
work first so long-running work survives). The raylet polls system
memory; past the threshold it terminates the most recently leased
worker, whose in-flight task fails back to its owner as a worker crash
and retries (possibly elsewhere / later, when memory frees).
"""
from __future__ import annotations

import time


def read_system_memory() -> tuple[int, int]:
    """(available_bytes, total_bytes) from /proc/meminfo (the reference
    reads the same file, cgroup-aware variant omitted)."""
    total = available = 0
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                available = int(line.split()[1]) * 1024
            if total and available:
                break
    return available, total


class MemoryMonitor:
    """Drives the kill policy from a pluggable usage reader (tests inject
    a fake reader; production uses /proc/meminfo)."""

    def __init__(self, raylet, threshold: float, min_interval_s: float = 1.0,
                 reader=read_system_memory):
        self.raylet = raylet
        self.threshold = threshold
        self.min_interval_s = min_interval_s
        self.reader = reader
        self._last_kill = 0.0
        self.kills: list[dict] = []  # observability

    def usage_fraction(self) -> float:
        available, total = self.reader()
        if total <= 0:
            return 0.0
        return 1.0 - (available / total)

    def maybe_kill(self) -> bool:
        """One poll: above threshold -> kill the newest leased worker
        (ref: worker_killing_policy 'newest first' — it is the most
        retriable and frees memory fastest)."""
        usage = self.usage_fraction()
        if usage < self.threshold:
            return False
        now = time.monotonic()
        if now - self._last_kill < self.min_interval_s:
            return False  # give the previous kill time to free memory
        victim = None
        victim_lease = None
        # two passes: plain task workers first (retriable), actor workers
        # only as a last resort (an actor with max_restarts=0 dies forever)
        for actors_allowed in (False, True):
            for lease in self.raylet.leases.values():
                if lease.worker.proc.poll() is not None:
                    continue
                if (lease.worker.actor_id is not None) != actors_allowed:
                    continue
                if victim_lease is None or lease.lease_id > victim_lease.lease_id:
                    victim_lease = lease
                    victim = lease.worker
            if victim is not None:
                break
        if victim is None:
            return False
        self._last_kill = now
        self.kills.append({
            "ts": time.time(),
            "usage": usage,
            "worker_pid": victim.proc.pid,
            "lease_id": victim_lease.lease_id,
        })
        try:
            victim.proc.kill()  # hard kill: the owner sees a worker crash
        except OSError:
            pass  # raced its own exit: the pressure is relieved either way
        return True

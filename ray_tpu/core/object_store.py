"""Python client for the C++ shared-memory object store.

Pairs the ctypes control path (create/seal/get/release with blocking waits in
native code) with an mmap of the same /dev/shm arena for zero-copy data
access — the role plasma's client plays in the reference
(ref: src/ray/core_worker/store_provider/plasma_store_provider.h:93), minus
the socket protocol: every process maps the arena directly.
"""

from __future__ import annotations

import ctypes
import mmap
import os

from ray_tpu._native import get_lib
from ray_tpu.devtools import chaos
from ray_tpu.utils import serialization
from ray_tpu.utils.ids import ObjectID


class ObjectStoreError(Exception):
    pass


class ObjectStoreFullError(ObjectStoreError):
    pass


class ObjectTimeoutError(ObjectStoreError):
    pass


class ChannelClosedError(ObjectStoreError):
    pass


class ObjectEvictedError(ObjectStoreError):
    """The object was sealed, then LRU-evicted: it is gone from this node.
    Callers surface ObjectLostError / trigger lineage reconstruction instead
    of blocking forever on a get."""


_ERRNAMES = {
    -1: "not found",
    -2: "already exists",
    -3: "out of memory",
    -4: "timeout",
    -5: "bad state",
    -6: "system error",
    -7: "closed",
    -8: "evicted",
}


def _check(rc: int, what: str):
    if rc == 0:
        return
    if rc == -3:
        raise ObjectStoreFullError(what)
    if rc == -4:
        raise ObjectTimeoutError(what)
    if rc == -7:
        raise ChannelClosedError(what)
    if rc == -8:
        raise ObjectEvictedError(what)
    raise ObjectStoreError(f"{what}: {_ERRNAMES.get(rc, rc)}")


class _ReleaseGuard:
    """Releases an object-store reference when the last zero-copy view dies."""

    __slots__ = ("_store", "_oid", "armed", "_done")

    def __init__(self, store: "SharedObjectStore", oid: ObjectID):
        self._store = store
        self._oid = oid
        self.armed = False
        self._done = False

    def release_now(self):
        if not self._done:
            self._done = True
            try:
                if self._store._handle:
                    self._store.release(self._oid)
            except Exception:  # raylint: disable=RT012 — guard __del__ path must never raise
                pass

    def __del__(self):
        if self.armed:
            self.release_now()


class SharedObjectStore:
    """Per-node shm object store client (also the creator on the raylet)."""

    def __init__(self, name: str, capacity: int | None = None, create: bool = False):
        self._lib = get_lib()
        self._name = name
        if create:
            assert capacity is not None
            self._handle = self._lib.rt_store_create(name.encode(), capacity)
        else:
            self._handle = self._lib.rt_store_connect(name.encode())
        if not self._handle:
            raise ObjectStoreError(
                f"could not {'create' if create else 'connect to'} store {name}"
            )
        self._created = create
        # python-side counters the native header has no slot for (the
        # spill writer lives in this process, so per-process is exact)
        self.spill_failures = 0
        path = "/dev/shm/" + name.lstrip("/")
        self._file = open(path, "r+b")
        self._mmap = mmap.mmap(self._file.fileno(), 0)
        self._view = memoryview(self._mmap)

    # -- raw object API ------------------------------------------------------

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        off = ctypes.c_uint64()
        rc = self._lib.rt_create(self._handle, object_id.binary(), size, ctypes.byref(off))
        _check(rc, f"create {object_id}")
        return self._view[off.value : off.value + size]

    def seal(self, object_id: ObjectID) -> None:
        if chaos.ENABLED:
            # "store.seal" fault point: an `error` action raises here as
            # an ObjectStoreError — exactly what a native seal failure
            # (chaos-armed or real) surfaces, so both travel one path
            try:
                chaos.point("store.seal", oid=object_id.hex())
            except chaos.ChaosError as e:
                raise ObjectStoreError(f"seal {object_id}: {e}") from e
        _check(self._lib.rt_seal(self._handle, object_id.binary()), f"seal {object_id}")

    def get_buffer(self, object_id: ObjectID, timeout_ms: int = -1) -> memoryview:
        """Blocking zero-copy view of a sealed object; takes a reference."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rt_get(
            self._handle, object_id.binary(), timeout_ms, ctypes.byref(off), ctypes.byref(size)
        )
        _check(rc, f"get {object_id}")
        return self._view[off.value : off.value + size.value]

    def contains(self, object_id: ObjectID) -> bool:
        return bool(self._lib.rt_contains(self._handle, object_id.binary()))

    def is_evicted(self, object_id: ObjectID) -> bool:
        """True if this id was sealed here and later LRU-evicted (tombstone)."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rt_get(
            self._handle, object_id.binary(), 0, ctypes.byref(off), ctypes.byref(size)
        )
        if rc == 0:  # present after all — drop the ref we just took
            self.release(object_id)
            return False
        return rc == -8

    def release(self, object_id: ObjectID) -> None:
        self._lib.rt_release(self._handle, object_id.binary())

    def delete(self, object_id: ObjectID) -> None:
        self._lib.rt_delete(self._handle, object_id.binary())

    @property
    def capacity(self) -> int:
        return self._lib.rt_store_capacity(self._handle)

    @property
    def bytes_in_use(self) -> int:
        return self._lib.rt_store_bytes_in_use(self._handle)

    # rt_store_stats field order (store.cc StoreStats)
    STAT_FIELDS = (
        "creates", "create_bytes", "seals", "gets", "get_waits", "get_lost",
        "releases", "deletes", "evictions", "evicted_bytes", "peak_bytes",
    )

    def stats(self) -> dict[str, int]:
        """Arena-wide counters from the shared header (store.cc
        StoreStats): every process mapping the arena reads the same
        numbers, so one metrics flush per node covers all clients."""
        out = (ctypes.c_uint64 * len(self.STAT_FIELDS))()
        n = self._lib.rt_store_stats(
            self._handle,
            ctypes.cast(out, ctypes.POINTER(ctypes.c_uint64)), len(out))
        d = {name: int(out[i]) for i, name in enumerate(self.STAT_FIELDS[:n])}
        d["bytes_in_use"] = int(self.bytes_in_use)
        d["capacity"] = int(self.capacity)
        d["spill_failures"] = int(self.spill_failures)
        return d

    def note_spill_failure(self) -> None:
        """Record one failed spill attempt (write error / chaos fault);
        surfaced through stats() so the backoff satellite is observable."""
        self.spill_failures += 1

    def list_spillable(self, max_count: int = 64) -> list[tuple[ObjectID, int]]:
        """Sealed, unreferenced objects in LRU order (spill candidates for
        the raylet's spill manager, ref: local_object_manager.h:42)."""
        ids = ctypes.create_string_buffer(20 * max_count)
        sizes = (ctypes.c_uint64 * max_count)()
        n = self._lib.rt_store_list_spillable(
            self._handle, ids,
            ctypes.cast(sizes, ctypes.POINTER(ctypes.c_uint64)), max_count)
        out = []
        for i in range(n):
            out.append((ObjectID(ids.raw[i * 20:(i + 1) * 20]), int(sizes[i])))
        return out

    # -- serialized object API ----------------------------------------------

    def put(self, object_id: ObjectID, value) -> int:
        """Serialize ``value`` directly into shm; returns stored size."""
        meta, buffers = serialization.dumps_with_buffers(value)
        size = serialization.total_size(meta, buffers)
        buf = self.create(object_id, size)
        serialization.pack_into(meta, buffers, buf)
        self.seal(object_id)
        return size

    def put_raw(self, object_id: ObjectID, payload) -> int:
        """Store pre-packed bytes (e.g. forwarded from another node)."""
        payload = memoryview(payload).cast("B")
        buf = self.create(object_id, payload.nbytes)
        buf[:] = payload
        self.seal(object_id)
        return payload.nbytes

    def get(self, object_id: ObjectID, timeout_ms: int = -1):
        """Deserialize a stored object.

        Zero-copy: array payloads alias the shm arena. The store reference
        taken by the underlying native get is tied to the deserialized views
        via a guard (see serialization._GuardedBuffer) and dropped when the
        last view is garbage-collected; values with no out-of-band buffers
        release the reference immediately.
        """
        buf = self.get_buffer(object_id, timeout_ms)
        guard = _ReleaseGuard(self, object_id)
        guard.armed = True
        try:
            value = serialization.unpack(buf, guard=guard)
        except Exception:
            guard.release_now()
            raise
        if not serialization.unpack_has_buffers(buf):
            guard.release_now()
        return value

    def try_get(self, object_id: ObjectID):
        """Non-blocking zero-copy read for the completion fast lane's
        caller-thread get: returns ``(value,)`` when the object is sealed
        locally, None when it is absent/pending/evicted — one native call,
        no contains()-then-get() race window."""
        try:
            return (self.get(object_id, timeout_ms=0),)
        except ObjectStoreError:
            return None

    # -- mutable channels (compiled-graph substrate) -------------------------

    def channel_create(self, object_id: ObjectID, size: int, num_readers: int) -> None:
        off = ctypes.c_uint64()
        rc = self._lib.rt_chan_create(
            self._handle, object_id.binary(), size, num_readers, ctypes.byref(off)
        )
        _check(rc, f"chan_create {object_id}")

    def channel_buffer(self, object_id: ObjectID) -> memoryview:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rt_chan_data(
            self._handle, object_id.binary(), ctypes.byref(off), ctypes.byref(size)
        )
        _check(rc, f"chan_data {object_id}")
        return self._view[off.value : off.value + size.value]

    def channel_write_acquire(self, object_id: ObjectID, timeout_ms: int = -1) -> memoryview:
        rc = self._lib.rt_chan_write_acquire(self._handle, object_id.binary(), timeout_ms)
        _check(rc, f"chan_write_acquire {object_id}")
        return self.channel_buffer(object_id)

    def channel_write_release(self, object_id: ObjectID, payload_size: int = 0) -> None:
        rc = self._lib.rt_chan_write_release(self._handle, object_id.binary(), payload_size)
        _check(rc, f"chan_write_release {object_id}")

    def channel_read_acquire(
        self, object_id: ObjectID, last_version: int, timeout_ms: int = -1
    ) -> tuple[memoryview, int]:
        """Returns (payload_view, version); payload_view is sized to the
        writer's payload_size (or the whole buffer for size-0 writers)."""
        version = ctypes.c_uint64()
        payload = ctypes.c_uint64()
        rc = self._lib.rt_chan_read_acquire(
            self._handle,
            object_id.binary(),
            last_version,
            timeout_ms,
            ctypes.byref(version),
            ctypes.byref(payload),
        )
        _check(rc, f"chan_read_acquire {object_id}")
        buf = self.channel_buffer(object_id)
        if payload.value:
            buf = buf[: payload.value]
        return buf, version.value

    def channel_read_release(self, object_id: ObjectID) -> None:
        rc = self._lib.rt_chan_read_release(self._handle, object_id.binary())
        _check(rc, f"chan_read_release {object_id}")

    def channel_close(self, object_id: ObjectID) -> None:
        self._lib.rt_chan_close(self._handle, object_id.binary())

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._handle:
            self._view.release()
            try:
                self._mmap.close()
            except BufferError:
                # zero-copy views handed out by get() still alias the mapping;
                # leave it to the process teardown to unmap.
                pass
            self._file.close()
            self._lib.rt_store_close(self._handle)
            self._handle = None

    def destroy(self) -> None:
        """Close and unlink the arena (creator only)."""
        name = self._name
        self.close()
        self._lib.rt_store_destroy(name.encode())

    def __del__(self):
        try:
            self.close()
        except Exception:  # raylint: disable=RT012 — __del__ may run at interpreter exit
            pass

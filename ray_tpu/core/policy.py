"""Shared scheduling policy: hybrid top-k node choice.

One implementation for both placement sites — GCS node pick and raylet
spillback (ref: hybrid_scheduling_policy.h:50 + policy/scorer.h): score
candidates by worst post-placement utilization on the requested
dimensions; randomize only among comfortable nodes (below the
utilization threshold) to avoid herding, else fall back to the single
best — a nearly-full node must never win a coin toss against an idle one.
"""

from __future__ import annotations

import random

# randomize among nodes whose worst post-placement utilization stays
# below this; above it, placement is deterministic best-first
UTIL_THRESHOLD = 0.75
TOP_K = 3


def fits(resources: dict, available: dict) -> bool:
    """Every requested dimension is available (1e-9 float slack)."""
    return all(available.get(k, 0.0) >= v - 1e-9
               for k, v in resources.items())


def score(resources: dict, total: dict, available: dict) -> float:
    """Worst post-placement utilization across the requested dimensions."""
    worst = 0.0
    for k, v in resources.items():
        cap = total.get(k, 0.0) or 1.0
        worst = max(worst, (cap - available.get(k, 0.0) + v) / cap)
    return worst


def pick(candidates: list[tuple[float, object]]):
    """candidates: [(score, item)]. Returns an item or None.

    Comfortable nodes (under the threshold) shadow tight ones, but the
    final choice is ALWAYS randomized over a set: concurrent requests
    act on gossip-stale views, and any deterministic pick herds them
    all onto one node until the next heartbeat."""
    if not candidates:
        return None
    candidates.sort(key=lambda si: si[0])
    comfortable = [i for s, i in candidates[:TOP_K] if s <= UTIL_THRESHOLD]
    if comfortable:
        return random.choice(comfortable)
    return random.choice([i for _, i in candidates[:TOP_K]])

"""Raylet: per-node daemon — worker pool, local scheduler, object transfer.

TPU-native equivalent of the reference raylet (ref: src/ray/raylet/
node_manager.h:124): grants resource-backed worker leases
(node_manager.proto:413 RequestWorkerLease semantics, including spillback
replies), forks and pools language workers (worker_pool.h:231), accounts
placement-group bundles with prepare/commit/return (ref:
placement_group_resource_manager.h), pulls remote objects into the node's
shm store (pull_manager.h:49 / push_manager.h:28 — here a direct
fetch-from-holder transfer driven by the GCS object directory), and
heartbeats resource views to the GCS (the RaySyncer role, ray_syncer.h:83).

One raylet owns one shm object store arena; several raylets can run on one
machine as virtual nodes — the multi-node-in-one-process test strategy the
reference uses (ref: python/ray/cluster_utils.py:135).
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import logging
import os
import pickle
import subprocess
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field

from ray_tpu.config import get_config
from ray_tpu.core import policy
from ray_tpu.core.object_store import ObjectStoreError, SharedObjectStore
from ray_tpu.devtools import chaos
from ray_tpu.utils import aio, metrics, rpc
from ray_tpu.utils.ids import NodeID, ObjectID, WorkerID

log = logging.getLogger(__name__)


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    proc: subprocess.Popen
    address: tuple[str, int] | None = None
    ready: asyncio.Event = field(default_factory=asyncio.Event)
    lease_id: int | None = None
    actor_id: bytes | None = None
    idle_since: float = 0.0
    language: str = "python"


@dataclass
class Lease:
    lease_id: int
    resources: dict[str, float]
    worker: WorkerHandle
    pg_key: tuple | None = None  # (pg_id, bundle_index) if inside a bundle
    owner_conn: object = None  # requester's connection: leases die with it
    tpu_chips: list | None = None  # chip ids granted to this lease


class PullBackPressure(Exception):
    """A queued pull/restore was shed at its admission deadline. Typed so
    the client plane can surface a serve-level BackPressureError with a
    retry hint instead of an opaque pull failure."""

    def __init__(self, message: str, retry_after_s: float = 0.1):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class PullAdmission:
    """PullManager-shaped admission window (ref: pull_manager.h:49):
    bounds the BYTES of concurrent restores/pulls in flight — not the
    request count — against a fixed budget and live arena headroom.
    Excess requests park FIFO; a parked request past its deadline is shed
    with :class:`PullBackPressure`, so a steal/adopt burst back-pressures
    instead of OOMing the receiving arena mid-decode."""

    def __init__(self, raylet):
        self.raylet = raylet
        self.max_bytes = max(1, int(raylet.cfg.pull_max_bytes_in_flight))
        self.in_flight = 0
        self.admitted = 0
        self.shed = 0
        self._q: collections.deque = collections.deque()
        self._pumping = False

    def stats(self) -> dict:
        return {"in_flight_bytes": int(self.in_flight),
                "queued": len(self._q),
                "admitted": int(self.admitted), "shed": int(self.shed)}

    async def acquire(self, nbytes: int, deadline: float | None = None):
        """Admit ``nbytes`` of inbound transfer, parking FIFO until the
        window (and the arena) has room or ``deadline`` passes."""
        nbytes = max(1, int(nbytes))
        if deadline is None:
            deadline = (time.monotonic()
                        + self.raylet.cfg.pull_admission_timeout_s)
        if not self._q and self._try_admit(nbytes):
            return
        fut = asyncio.get_running_loop().create_future()
        self._q.append((nbytes, deadline, fut))
        if not self._pumping:
            self._pumping = True
            self.raylet._bg.spawn(self._pump_loop())
        await fut

    def release(self, nbytes: int):
        self.in_flight = max(0, self.in_flight - max(1, int(nbytes)))
        self._pump()

    def _retry_hint(self) -> float:
        queued = sum(n for n, _, _ in self._q)
        return min(2.0, max(0.05,
                            0.1 * (self.in_flight + queued) / self.max_bytes))

    def _try_admit(self, nbytes: int) -> bool:
        if self.in_flight + nbytes > self.max_bytes:
            # an object larger than the whole window still admits when
            # alone — the budget bounds concurrency, it must not strand
            # a single oversized pull forever
            if not (self.in_flight == 0 and nbytes > self.max_bytes):
                return False
        store, cfg = self.raylet.store, self.raylet.cfg
        if store is not None and cfg.object_spilling_threshold > 0:
            cap = max(1, store.capacity)
            used = store.bytes_in_use + self.in_flight
            if used + nbytes > cap:
                # truly would not fit: park until spill frees headroom
                self.raylet._bg.spawn(
                    self.raylet._spill_until_low_water(extra_need=nbytes))
                return False
            if used + nbytes > cfg.object_spilling_threshold * cap:
                # fits, but crosses the pressure line: admit and kick the
                # spiller so headroom recovers behind the transfer
                self.raylet._bg.spawn(
                    self.raylet._spill_until_low_water(extra_need=nbytes))
        self.in_flight += nbytes
        self.admitted += 1
        return True

    def _pump(self):
        now = time.monotonic()
        while self._q:
            nbytes, deadline, fut = self._q[0]
            if fut.done():
                self._q.popleft()
                continue
            if now >= deadline:
                self._q.popleft()
                self.shed += 1
                fut.set_exception(PullBackPressure(
                    f"pull admission shed at deadline ({self.in_flight}B in "
                    f"flight, window {self.max_bytes}B)",
                    retry_after_s=self._retry_hint()))
                continue
            if not self._try_admit(nbytes):
                return  # strict FIFO: a blocked head parks the queue
            self._q.popleft()
            fut.set_result(True)

    async def _pump_loop(self):
        # deadline sheds and arena-headroom recoveries need a clock even
        # when no release() fires; cheap poll only while anyone waits
        try:
            while self._q:
                self._pump()
                await asyncio.sleep(0.05)
        finally:
            self._pumping = False


# Fixed-point resource quantum (ref: src/ray/common/scheduling/
# fixed_point.h — 1/10000 granules). All ledger arithmetic is integral so
# allocate/free cycles of fractional demands (0.1 CPU x 10) can never
# drift a slot away through float error.
FP_ONE = 10_000


def _fp(v: float) -> int:
    return round(v * FP_ONE)


def _fp_dict(d: dict[str, float]) -> dict[str, int]:
    return {k: _fp(v) for k, v in d.items()}


def _unfp_dict(d: dict[str, int]) -> dict[str, float]:
    return {k: v / FP_ONE for k, v in d.items()}


class ResourceLedger:
    """Fractional resource accounting for one node, incl. PG bundles
    (ref: src/ray/common/scheduling/resource_instance_set.h semantics,
    simplified to totals — per-slot TPU instance tracking lives in the
    accelerator layer). Internally fixed-point; the dict[str, float] API
    converts at the boundary."""

    def __init__(self, total: dict[str, float]):
        self._total = _fp_dict(total)
        self._available = dict(self._total)
        # (pg_id, bundle_index) -> {"resources": ..., "available": ..., "committed": bool}
        self.bundles: dict[tuple, dict] = {}

    @property
    def total(self) -> dict[str, float]:
        return _unfp_dict(self._total)

    @property
    def available(self) -> dict[str, float]:
        return _unfp_dict(self._available)

    def fits(self, req: dict[str, float]) -> bool:
        return all(self._available.get(k, 0) >= _fp(v) for k, v in req.items())

    def allocate(self, req: dict[str, float]) -> bool:
        if not self.fits(req):
            return False
        for k, v in req.items():
            self._available[k] = self._available.get(k, 0) - _fp(v)
        return True

    def free(self, req: dict[str, float]) -> None:
        for k, v in req.items():
            cap = self._total.get(k, _fp(v))
            self._available[k] = min(self._available.get(k, 0) + _fp(v), cap)

    # -- placement group bundles ------------------------------------------
    def prepare_bundle(self, key: tuple, resources: dict[str, float]) -> bool:
        b = self.bundles.get(key)
        if b is not None:
            # 2PC retry over an already-held reservation: refresh the
            # lease stamp so the GC clock restarts with the new round
            b["prepared_at"] = time.monotonic()
            return True
        if not self.allocate(resources):
            return False
        self.bundles[key] = {
            "resources": _fp_dict(resources),
            "available": _fp_dict(resources),
            "committed": False,
            # prepared-but-uncommitted reservations carry a lease: if the
            # coordinating GCS dies between prepare and commit, the
            # raylet-side GC (Raylet._gc_stale_bundles) reclaims the
            # capacity after cfg.pg_bundle_lease_s instead of leaking it
            # forever
            "prepared_at": time.monotonic(),
        }
        return True

    def commit_bundle(self, key: tuple) -> bool:
        b = self.bundles.get(key)
        if b is None:
            return False
        b["committed"] = True
        return True

    def return_bundle(self, key: tuple) -> None:
        b = self.bundles.pop(key, None)
        if b is not None:
            self.free(_unfp_dict(b["resources"]))

    def bundle_allocate(self, key: tuple, req: dict[str, float]) -> bool:
        b = self.bundles.get(key)
        if b is None or not b["committed"]:
            return False
        if not all(b["available"].get(k, 0) >= _fp(v) for k, v in req.items()):
            return False
        for k, v in req.items():
            b["available"][k] -= _fp(v)
        return True

    def bundle_free(self, key: tuple, req: dict[str, float]) -> None:
        b = self.bundles.get(key)
        if b is None:
            return
        for k, v in req.items():
            cap = b["resources"].get(k, _fp(v))
            b["available"][k] = min(b["available"].get(k, 0) + _fp(v), cap)

    def held_bundles(self) -> list[dict]:
        """The wire shape bundle reservations travel in (register_node
        reports, rpc_list_bundles audits) — shared by the real raylet
        and the churn harness's SimRaylet so they can't drift."""
        return [
            {"pg_id": key[0], "bundle_index": key[1],
             "resources": _unfp_dict(b["resources"]),
             "committed": bool(b.get("committed"))}
            for key, b in self.bundles.items()
        ]

    def gc_stale_bundles(self, now: float, lease_s: float) -> list[tuple]:
        """Return (and free) prepared-but-never-committed reservations
        whose lease expired: the coordinating GCS died (or gave up)
        mid-2PC, so nothing will ever commit or return them. Returns the
        reclaimed keys."""
        if lease_s <= 0:
            return []
        stale = [key for key, b in self.bundles.items()
                 if not b.get("committed")
                 and now - b.get("prepared_at", now) > lease_s]
        for key in stale:
            self.return_bundle(key)
        return stale


class Raylet:
    def __init__(
        self,
        gcs_address: tuple[str, int],
        resources: dict[str, float] | None = None,
        store_capacity: int | None = None,
        host: str = "127.0.0.1",
        labels: dict[str, str] | None = None,
        session: str = "",
    ):
        self.cfg = get_config()
        self.node_id = NodeID.generate()
        self.gcs_address = gcs_address
        self.host = host
        self.labels = labels or {}
        # per-chip TPU instance tracking (ref: the reference's per-slot
        # resource_instance_set; chips are handed to leases by id so workers
        # can isolate via TPU_VISIBLE_CHIPS)
        self._tpu_chips_free: list[str] = [
            str(i) for i in range(int((resources or {}).get("TPU", 0)))
        ]
        self._worker_chips: dict = {}  # worker_id -> list[str]
        self.session = session or f"s{os.getpid()}"

        if resources is None:
            resources = {"CPU": float(os.cpu_count() or 1)}
        resources.setdefault("node", 1.0)
        if "memory" not in resources:
            # advertise system memory (bytes) so memory-capped leases are
            # schedulable (ref: memory as a default node resource)
            try:
                from ray_tpu.core.memory_monitor import read_system_memory

                resources["memory"] = float(read_system_memory()[1])
            except (OSError, ValueError):
                pass  # no /proc: memory simply isn't advertised
        self.ledger = ResourceLedger(resources)

        self.log_dir = os.path.join(
            "/tmp", "ray_tpu", f"session_{self.session}", "logs"
        )
        self.store_name = f"/rt_{self.session}_{self.node_id.hex()[:8]}"
        self.store = SharedObjectStore(
            self.store_name,
            capacity=store_capacity or self.cfg.object_store_memory,
            create=True,
        )

        self.server = rpc.make_server(host, 0)
        self.server.add_routes(self)
        self.server.on_disconnect = self._on_client_disconnect
        self.gcs: rpc.Connection | None = None

        self._lease_ids = itertools.count(1)
        self._spread_rr = 0  # SPREAD strategy round-robin cursor
        self._view_versions = itertools.count(1)  # resource-view sync versions
        self.leases: dict[int, Lease] = {}
        self.idle_workers: list[WorkerHandle] = []
        self.all_workers: dict[WorkerID, WorkerHandle] = {}
        self._pending_lease_q: asyncio.Queue = asyncio.Queue()
        self._lease_waiters: list[tuple[dict, asyncio.Future, tuple | None]] = []
        # client-reported task backlog (work queued driver-side that is not
        # a parked lease request), summed into the heartbeat demand signal
        # (ref: autoscaler v2 resource-demand reporting, autoscaler.proto)
        # keyed by the live Connection OBJECT (identity hash): an id()
        # key could alias a new connection after CPython address reuse,
        # letting a dead client's stale backlog skew the autoscaler
        # demand signal. The dict entry pins the conn until disconnect
        # pops it, so aliasing is impossible.
        self._demand_reports: dict[object, int] = {}
        self.cluster_view: list[dict] = []
        # object spilling (ref: local_object_manager.h:42): sealed objects
        # move to disk under arena pressure and restore on demand
        self._spilled: dict[ObjectID, str] = {}  # oid -> file path
        self._spill_lock = asyncio.Lock()
        # Guards the _spilling_now/_freed_while_spilling handshake between
        # the loop thread (_drop_spill_file) and spill executor threads
        # (_spill_one's finally) — membership check + marker add must be
        # atomic or a freed-during-spill file leaks.
        self._spill_state_lock = threading.Lock()
        self._spilling_now: set[ObjectID] = set()
        self._freed_while_spilling: set[ObjectID] = set()
        self._spill_failed_at: dict[ObjectID, float] = {}
        self._spill_fail_n: dict[ObjectID, int] = {}  # consecutive failures
        # observability plane: object-store watermark history (the spill
        # trigger reads the recent PEAK, not one instant) plus lease
        # lifecycle cumulatives, both published as a hand-rolled snapshot
        # under ns="metrics" key raylet.<node> — never the process-global
        # registry, which an in-process topology shares with the driver
        # (same double-count hazard as the GCS's _trace_metrics_tick)
        from ray_tpu.core.metrics_store import WatermarkTracker

        self._store_watermark = WatermarkTracker()
        self._lease_stats = {"granted": 0, "returned": 0,
                             "owner_disconnect": 0, "worker_death": 0}
        self._metrics_published_at = 0.0
        base = self.cfg.object_spilling_dir or os.path.join(
            self.cfg.temp_dir, f"session_{self.session}", "spill")
        self.spill_dir = os.path.join(base, self.node_id.hex()[:12])
        # cooperative spill: client processes that registered arena-owner
        # providers (prefix cache, shard plane, staging) by RPC address
        self._spill_providers: set[tuple] = set()
        self._provider_conns: dict[tuple, object] = {}
        # tier-1 peer serving: (conn, oid) -> open spill-file fd, so a
        # concurrent unlink can't tear a chunked transfer mid-stream
        self._spill_serves: dict[tuple, tuple] = {}
        # object transfer: coalesce duplicate pulls + byte-budget admission
        # of inbound restores/pulls (ref: pull_manager.h:49)
        self._active_pulls: dict[ObjectID, asyncio.Future] = {}
        self._pull_admission = PullAdmission(self)
        self._transfer_pins: dict[tuple, bool] = {}  # (conn, oid) -> pinned
        # node tunnel (core/tunnel.py): this raylet terminates its node's
        # end of every driver<->node tunnel and routes record frames to
        # local workers over cached raylet->worker connections
        self._tunnel_ids = itertools.count(1)
        self._tunnel_lanes: dict[int, dict] = {}   # lane -> routing entry
        self._tunnel_worker_conns: dict[WorkerID, object] = {}
        self._stopping = False
        self._bg = aio.TaskGroup()
        self.memory_monitor = None
        if self.cfg.memory_usage_threshold > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor

            self.memory_monitor = MemoryMonitor(
                self, self.cfg.memory_usage_threshold,
                self.cfg.memory_monitor_refresh_s,
            )
        # kernel-enforced per-worker memory caps ("physical execution
        # mode", ref: cgroup_manager.h); advisory monitor still runs when
        # the hierarchy isn't writable
        from ray_tpu.core.cgroup import CgroupManager, detect_driver

        driver = detect_driver() if self.cfg.enable_worker_cgroups else None
        self.cgroups = CgroupManager(self.node_id.hex(), driver)

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> tuple[str, int]:
        addr = await self.server.start()
        self.gcs = await rpc.connect(*self.gcs_address, timeout=self.cfg.rpc_connect_timeout_s)
        self.gcs.on_message = self._on_gcs_push
        reply = await self.gcs.call(
            "register_node",
            {
                "node_id": self.node_id,
                "address": addr,
                "store_name": self.store_name,
                "resources": self.ledger.total,
                "labels": self.labels,
                "pid": os.getpid(),
                "bundles": self._held_bundles(),
            },
        )
        self.cluster_view = reply["cluster"]
        self._apply_bundle_reconciliation(reply)
        await self.gcs.call("subscribe", {"channel": "nodes"})
        self._bg.spawn(self._heartbeat_loop())
        self._bg.spawn(self._reaper_loop())
        if self.cfg.object_spilling_threshold > 0:
            self._bg.spawn(self._spill_monitor_loop())
        return addr

    async def _reconnect_gcs(self):
        """Dial a (possibly restarted) GCS and re-establish registration.
        The old connection closes only AFTER re-registration replaced its
        mapping server-side — closing first would read as a node death."""
        conn = await rpc.connect(*self.gcs_address, timeout=5)
        old = self.gcs
        self.gcs = conn
        self.gcs.on_message = self._on_gcs_push
        await self._reregister()
        if old is not None:
            try:
                await old.close()
            except (rpc.RpcError, OSError):
                pass  # replacing a dead connection: close is best-effort

    async def _reregister(self):
        # held bundles ride the registration so a restarted GCS can
        # reconcile its recovered pgs table against what this node's
        # ledger actually reserves (adopt committed bundles, order stale
        # ones returned)
        reply = await self.gcs.call(
            "register_node",
            {
                "node_id": self.node_id,
                "address": self.server.address,
                "store_name": self.store_name,
                "resources": self.ledger.total,
                "labels": self.labels,
                "pid": os.getpid(),
                "bundles": self._held_bundles(),
            },
        )
        self.cluster_view = reply["cluster"]
        self._apply_bundle_reconciliation(reply)
        await self.gcs.call("subscribe", {"channel": "nodes"})

    def _apply_bundle_reconciliation(self, reply: dict) -> None:
        stale = reply.get("return_bundles") or ()
        for key in stale:
            self.ledger.return_bundle(tuple(key))
        if stale:
            self._grant_waiters()

    def _on_gcs_push(self, msg):
        if msg.get("m") == "pubsub" and msg["p"]["channel"] == "nodes":
            event = msg["p"]["message"]
            if event.get("event") in ("added", "updated"):
                node = event["node"]
                for n in self.cluster_view:
                    if n["node_id"] != node["node_id"]:
                        continue
                    # versioned apply (ray_syncer.h:83): a reordered push
                    # must not roll the peer's view back to an older state
                    if node.get("view_version", 0) < n.get("view_version", 0):
                        return
                    break
                self.cluster_view = [
                    n for n in self.cluster_view if n["node_id"] != node["node_id"]
                ]
                self.cluster_view.append(node)
            elif event.get("event") == "removed":
                self.cluster_view = [
                    n for n in self.cluster_view if n["node_id"] != event["node_id"]
                ]

    async def _heartbeat_loop(self):
        failures = 0
        while not self._stopping:
            try:
                reply = await self.gcs.call(
                    "heartbeat",
                    {"node_id": self.node_id,
                     "resources_available": self.ledger.available,
                     # monotone view version: the GCS and peers drop
                     # reordered/stale reports (ray_syncer.h versioning)
                     "version": next(self._view_versions),
                     # demand signal for the autoscaler (ref: autoscaler v2
                     # resource-demand reporting): parked lease requests
                     # plus client-reported driver-side backlog
                     "queued_leases": len(self._lease_waiters)
                     + sum(self._demand_reports.values())},
                )
                failures = 0
                if isinstance(reply, dict) and not reply.get("ok", True):
                    # a restarted GCS doesn't know this node: re-register
                    # (the GCS-FT reconnection path, ref: gcs_client
                    # reconnection in accessor.h)
                    await self._reregister()
            except Exception:
                failures += 1
                if failures >= 3:
                    try:
                        await self._reconnect_gcs()
                        failures = 0
                    except Exception:
                        log.debug("GCS reconnect attempt failed",
                                  exc_info=True)
            await asyncio.sleep(self.cfg.health_check_period_s)

    async def _reaper_loop(self):
        """Reap dead worker processes; free leases; trim the idle pool;
        poll the memory monitor (OOM protection)."""
        last_mem_check = 0.0
        while not self._stopping:
            await asyncio.sleep(0.2)
            now = time.monotonic()
            if (self.memory_monitor is not None
                    and now - last_mem_check >= self.cfg.memory_monitor_refresh_s):
                last_mem_check = now
                try:
                    self.memory_monitor.maybe_kill()
                except Exception:
                    log.debug("memory monitor sweep failed", exc_info=True)
            self._gc_stale_bundles(now)
            for w in list(self.all_workers.values()):
                if w.proc.poll() is not None:
                    await self._on_worker_death(w)
            # trim idle workers beyond the warm minimum, counted per
            # language: an idle cpp worker must not occupy the python warm
            # slot (or vice versa) — pools are language-segregated
            keep: list[WorkerHandle] = []
            kept_by_lang: dict[str, int] = {}
            for w in self.idle_workers:
                if (
                    kept_by_lang.get(w.language, 0) >= self.cfg.min_idle_workers
                    and now - w.idle_since > self.cfg.worker_lease_timeout_s
                ):
                    w.proc.terminate()
                    self.all_workers.pop(w.worker_id, None)
                    self._release_cgroup_after_exit(w)
                    # trimmed workers never run their clean-exit recorder
                    # unlink and skip the death-report path: drop the file
                    # here or it leaks 256KB per trim for the session
                    from ray_tpu.utils import recorder as _recorder

                    try:
                        os.unlink(_recorder.worker_recorder_path(
                            self.cfg.temp_dir, self.session,
                            w.worker_id.hex()))
                    except OSError:
                        pass
                else:
                    keep.append(w)
                    kept_by_lang[w.language] = kept_by_lang.get(w.language, 0) + 1
            self.idle_workers = keep

    def _release_cgroup_after_exit(self, w: WorkerHandle):
        """rmdir of a leaf fails EBUSY while the (just-terminated) process
        is still listed in cgroup.procs — release only after it exits."""
        if not self.cgroups.enabled:
            return

        async def waiter():
            deadline = time.monotonic() + 10.0
            while w.proc.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            self.cgroups.release_worker(w.worker_id.hex())

        self._bg.spawn(waiter(), asyncio.get_running_loop())

    async def _on_worker_death(self, w: WorkerHandle):
        self.all_workers.pop(w.worker_id, None)
        self._reap_tunnel_lanes_for_worker(w.worker_id)
        self.cgroups.release_worker(w.worker_id.hex())  # already exited
        if w in self.idle_workers:
            self.idle_workers.remove(w)
        if w.lease_id is not None and w.lease_id in self.leases:
            lease = self.leases.pop(w.lease_id)
            self._lease_stats["worker_death"] += 1
            self._free_lease_resources(lease)
            self._grant_waiters()
        await self._report_worker_death(w)
        if w.actor_id is not None:
            try:
                await self.gcs.call(
                    "report_actor_death",
                    {"actor_id": w.actor_id, "cause": f"worker pid={w.proc.pid} exited"},
                )
            except Exception:
                log.debug("actor death report failed", exc_info=True)

    async def _report_worker_death(self, w: WorkerHandle):
        """Postmortem: the victim's flight-recorder ring lives in a shm
        file under the session tree (utils/recorder.py), so it survives
        a SIGKILL — dump the last-N stage events plus exit context into
        the GCS death-report table (state.list_worker_deaths). A clean
        exit_worker unlinks its recorder first, so only real deaths
        carry events."""
        from ray_tpu.utils import recorder as _recorder

        rec_path = _recorder.worker_recorder_path(
            self.cfg.temp_dir, self.session, w.worker_id.hex())
        events = _recorder.read_events(rec_path, last=64)
        try:
            os.unlink(rec_path)
        except OSError:
            pass
        returncode = w.proc.poll()
        report = {
            "worker_id": w.worker_id.hex(),
            "node_id": self.node_id.hex(),
            "pid": w.proc.pid,
            "ts": time.time(),
            "returncode": returncode,
            # negative returncode = killed by that signal (SIGKILL -> -9)
            "signal": -returncode if returncode and returncode < 0 else None,
            "actor_id": w.actor_id.hex()
                        if hasattr(w.actor_id, "hex") else w.actor_id,
            "leased": w.lease_id is not None,
            "recorder_events": events,
        }
        try:
            await self.gcs.call("kv_put", {
                "ns": "worker_deaths", "key": w.worker_id.hex(),
                "value": pickle.dumps(report)})
        except Exception:
            # GCS unreachable: the death still frees the lease above
            log.debug("worker death report failed", exc_info=True)

    # ---------------------------------------------------------- worker pool
    def _spawn_worker(self, language: str = "python") -> WorkerHandle:
        worker_id = WorkerID.generate()
        env = dict(os.environ)
        env.update(self.cfg.to_env())
        pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_parent + os.pathsep + env.get("PYTHONPATH", "")
        env.update(
            {
                "RT_WORKER_ID": worker_id.hex(),
                "RT_RAYLET_HOST": self.server.address[0],
                "RT_RAYLET_PORT": str(self.server.address[1]),
                "RT_GCS_HOST": self.gcs_address[0],
                "RT_GCS_PORT": str(self.gcs_address[1]),
                "RT_STORE_NAME": self.store_name,
                "RT_NODE_ID": self.node_id.hex(),
                "RT_SESSION": self.session,
            }
        )
        if language == "cpp":
            # C++ worker binary (rt_cpp_worker.cc runtime + user RT_REMOTE
            # functions), pointed at via RT_CPP_WORKER (ref: cpp/ worker API)
            binary = os.environ.get("RT_CPP_WORKER") or self.cfg.cpp_worker_binary
            if not binary:
                from ray_tpu.core.ref import ConfigurationError

                raise ConfigurationError(
                    "cpp task submitted but no C++ worker binary configured "
                    "(set RT_CPP_WORKER=<path to binary built against "
                    "rt_cpp_api.h>)"
                )
            argv = [binary]
        else:
            argv = [sys.executable, "-m", "ray_tpu.core.worker"]
        # per-worker log files (ref: the /tmp/ray/session_*/logs tree +
        # pipe_logger.h redirection): stdout/err land in the session log dir
        # and are served back via rpc_get_log / state.get_log
        out_f = err_f = None
        try:
            os.makedirs(self.log_dir, exist_ok=True)
            stem = os.path.join(self.log_dir, f"worker-{worker_id.hex()[:12]}")
            out_f = open(stem + ".out", "ab")
            err_f = open(stem + ".err", "ab")
        except OSError:
            if out_f is not None:
                out_f.close()  # .err open failed: don't leak the .out fd
            out_f = err_f = None  # unwritable tmp: inherit the raylet's fds
        proc = subprocess.Popen(argv, env=env, stdout=out_f, stderr=err_f)
        if out_f is not None:
            out_f.close()
            err_f.close()
        w = WorkerHandle(worker_id=worker_id, proc=proc, language=language)
        self.all_workers[worker_id] = w
        self.cgroups.isolate_worker(worker_id.hex(), proc.pid, None)
        return w

    async def _proxy_worker_call(self, p, method: str, payload: dict,
                                 timeout: float = 10.0):
        """Proxy an on-demand RPC to one of this node's workers (ref:
        dashboard reporter profiling endpoints). worker_id may be a hex
        prefix; unique match required. Degrades to None (like get_log)
        for missing/ambiguous ids, dead workers, and workers that don't
        speak the RPC (C++)."""
        prefix = (p.get("worker_id") or "")
        if not prefix:
            return None
        matches = [w for wid, w in self.all_workers.items()
                   if wid.hex().startswith(prefix)]
        if len(matches) != 1 or matches[0].address is None:
            return None
        try:
            wconn = await rpc.connect(*matches[0].address, timeout=5)
            try:
                return await wconn.call(method, payload, timeout=timeout)
            finally:
                await wconn.close()
        except Exception:
            return None

    async def rpc_dump_worker_stack(self, conn, p):
        return await self._proxy_worker_call(p, "dump_stack", {})

    async def rpc_heap_profile_worker(self, conn, p):
        """Proxy heap-profile control/snapshots to a worker (the memray /
        profile_manager.py:191 role; tracemalloc in-process)."""
        return await self._proxy_worker_call(
            p, "heap_profile",
            {k: p[k] for k in ("action", "top", "nframes") if k in p})

    async def rpc_cpu_profile_worker(self, conn, p):
        """Proxy a sampled CPU profile (flamegraph data) to a worker (ref:
        profile_manager.py:82 py-spy `record` role; in-process sampler)."""
        duration = min(float(p.get("duration_s", 2.0)), 30.0)
        return await self._proxy_worker_call(
            p, "cpu_profile",
            {k: p[k] for k in ("duration_s", "interval_s") if k in p},
            timeout=duration + 10.0)

    async def rpc_get_log(self, conn, p):
        """Serve a worker's captured stdout/stderr tail (ref: state API
        get_log over the dashboard log tree). p: worker_id (hex prefix ok),
        stream ("out"|"err"), tail bytes."""
        stream = p.get("stream", "out")
        if stream not in ("out", "err"):
            return None
        prefix = (p.get("worker_id") or "")[:12]
        if not prefix:
            return None
        path = os.path.join(self.log_dir, f"worker-{prefix}.{stream}")
        if not os.path.exists(path):
            # short hex prefixes are allowed: resolve by glob, unique match
            import glob as _glob

            matches = _glob.glob(
                os.path.join(self.log_dir, f"worker-{prefix}*.{stream}"))
            if len(matches) != 1:
                return None
            path = matches[0]
        tail = int(p.get("tail", 64 * 1024))
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail))
                return f.read().decode(errors="replace")
        except OSError:
            return None

    async def rpc_get_lease_env(self, conn, p):
        """Worker-side query for its accelerator assignment (applied as
        TPU_VISIBLE_CHIPS before the first user code runs)."""
        from ray_tpu.utils.ids import WorkerID as _WID

        chips = self._worker_chips.get(_WID.from_hex(p["worker_id"]))
        return {"tpu_chips": chips}

    async def rpc_kill_worker(self, conn, p):
        """Force-kill a worker (task cancellation with force=True; ref:
        CancelTask force_kill path)."""
        from ray_tpu.utils.ids import WorkerID as _WID

        w = self.all_workers.get(_WID.from_hex(p["worker_id"]))
        if w is None:
            return False
        try:
            w.proc.kill()
        except Exception:
            return False
        return True

    async def rpc_worker_ready(self, conn, p):
        w = self.all_workers.get(WorkerID.from_hex(p["worker_id"]))
        if w is None:
            return {"ok": False}
        w.address = tuple(p["address"])
        w.ready.set()
        return {"ok": True}

    async def _pop_worker(self, language: str = "python") -> WorkerHandle:
        # language-segregated pop (ref: worker_pool.h:231 per-language pools)
        for i in range(len(self.idle_workers) - 1, -1, -1):
            if self.idle_workers[i].language != language:
                continue
            w = self.idle_workers.pop(i)
            if w.proc.poll() is None:
                return w
            await self._on_worker_death(w)
        w = self._spawn_worker(language)
        try:
            await asyncio.wait_for(w.ready.wait(), timeout=self.cfg.worker_start_timeout_s)
        except asyncio.TimeoutError:
            w.proc.kill()
            self.all_workers.pop(w.worker_id, None)
            self._release_cgroup_after_exit(w)
            raise RuntimeError("worker failed to start in time")
        return w

    # --------------------------------------------------------------- leases
    async def rpc_lease_worker(self, conn, p):
        """Grant a worker lease, spill back, or queue until resources free.

        Mirrors HandleRequestWorkerLease (ref: node_manager.cc:1886 →
        cluster_task_manager.h:44): local grant if resources fit now;
        otherwise if another node in the synced cluster view fits, reply
        with a spillback address; otherwise queue (infeasible-now).
        """
        resources = dict(p.get("resources") or {"CPU": 1.0})
        if chaos.ENABLED:
            # "raylet.lease_grant" fault point: `error` raises out of the
            # handler (the requester's lease RPC fails — its retry/
            # spillback logic must absorb it); `drop` refuses the grant
            # explicitly; `delay` stalls this raylet's loop like an
            # overloaded node manager would
            act = chaos.point("raylet.lease_grant",
                              cpus=float(resources.get("CPU", 0.0)))
            if act is not None and act.kind == "drop":
                raise rpc.RpcError("chaos: lease grant dropped")
        pg_key = None
        if p.get("pg_id") is not None:
            pg_key = (p["pg_id"], p.get("bundle_index", 0))
        strategy = p.get("strategy")
        if strategy is not None:
            redirect = self._apply_strategy(strategy, resources, p)
            if redirect is not None:
                return redirect
        granted = self._try_allocate(resources, pg_key)
        if not granted:
            spill = self._pick_spillback(resources, p)
            if spill is not None:
                return {"granted": False, "spill_to": spill}
            fut = asyncio.get_running_loop().create_future()
            self._lease_waiters.append((resources, fut, pg_key, conn))
            try:
                await fut  # resolved by _grant_waiters when resources free up
            except asyncio.CancelledError:
                # requester disconnected while queued (see _on_disconnect)
                if fut.done() and not fut.cancelled():
                    self._free_resources(resources, pg_key)
                raise
        return await self._grant_lease(conn, p, resources, pg_key)

    async def _grant_lease(self, conn, p, resources, pg_key) -> dict:
        """Shared grant tail (resources already allocated): pop/spawn a
        worker, stamp the lease, build the reply. On failure the
        allocation is returned."""
        if conn._closed:
            # requester died between grant and reply: give the slot back
            self._free_resources(resources, pg_key)
            self._grant_waiters()
            raise rpc.RpcError("lease requester disconnected")
        try:
            w = await self._pop_worker(p.get("language") or "python")
        except Exception:
            self._free_resources(resources, pg_key)
            raise
        lease_id = next(self._lease_ids)
        w.lease_id = lease_id
        # the lease's memory resource becomes a kernel cap; None RESETS the
        # cap so a recycled worker can't inherit the previous lease's limit
        mem = resources.get("memory")
        self.cgroups.set_limit(w.worker_id.hex(), int(mem) if mem else None)
        tpu_chips = None
        n_tpu = int(resources.get("TPU", 0))
        if n_tpu > 0 and self._tpu_chips_free:
            tpu_chips = [self._tpu_chips_free.pop(0) for _ in range(min(n_tpu, len(self._tpu_chips_free)))]
            self._worker_chips[w.worker_id] = tpu_chips
        if p.get("for_actor") is not None:
            w.actor_id = p["for_actor"]
        # A lease dies with its owner's connection only when the owner says
        # so (core_client sets owner_bound on its persistent raylet conn).
        # Actor leases and spillback leases arrive over transient connections
        # that close right after the grant — reaping those would kill the
        # worker we just handed out.
        owner_conn = conn if p.get("owner_bound") else None
        self.leases[lease_id] = Lease(lease_id, resources, w, pg_key, owner_conn, tpu_chips)
        self._lease_stats["granted"] += 1
        return {
            "granted": True,
            "lease_id": lease_id,
            "worker_address": w.address,
            "worker_id": w.worker_id.hex(),
            "node_id": self.node_id,
            "tpu_chips": tpu_chips,
        }

    async def rpc_lease_workers(self, conn, p):
        """Batched lease grants (protocol 2.0): allocate every fitting
        request in ONE ledger pass, then pop/spawn the granted workers in
        parallel. Non-fitting requests never park (a parked item would
        hold its whole batch hostage): they reply spillback or
        ``busy`` and the caller's retry loop (the GCS actor scheduler)
        re-sends. One reply list, positionally matching ``requests``."""
        requests = p["requests"]
        out: list = [None] * len(requests)
        granted: list = []
        # one ledger pass: allocation order is batch order
        for i, req in enumerate(requests):
            resources = dict(req.get("resources") or {"CPU": 1.0})
            if chaos.ENABLED:
                # per-request verdict, absorbed per slot: an injected
                # `error` must fail THIS request only — raising out of
                # the handler here would abort batch-mates whose ledger
                # allocations are already committed (a capacity leak)
                try:
                    act = chaos.point("raylet.lease_grant",
                                      cpus=float(resources.get("CPU", 0.0)),
                                      batch=len(requests))
                except chaos.ChaosError as e:
                    out[i] = {"granted": False, "busy": True,
                              "error": f"chaos: {e}"}
                    continue
                if act is not None and act.kind == "drop":
                    out[i] = {"granted": False, "busy": True,
                              "error": "chaos: lease grant dropped"}
                    continue
            pg_key = None
            if req.get("pg_id") is not None:
                pg_key = (req["pg_id"], req.get("bundle_index", 0))
            if self._try_allocate(resources, pg_key):
                granted.append((i, resources, pg_key, req))
            else:
                spill = self._pick_spillback(resources, req)
                out[i] = ({"granted": False, "spill_to": spill}
                          if spill is not None
                          else {"granted": False, "busy": True})

        async def grant(i, resources, pg_key, req):
            try:
                out[i] = await self._grant_lease(conn, req, resources, pg_key)
            except Exception as e:
                out[i] = {"granted": False, "busy": True, "error": repr(e)}

        if len(granted) == 1:
            await grant(*granted[0])
        elif granted:
            await asyncio.gather(*(grant(*g) for g in granted))
        return out

    def _apply_strategy(self, strategy: dict, resources: dict, p: dict):
        """Strategy-directed placement at the lease site (ref: raylet
        scheduling policies — spread_scheduling_policy.cc,
        node_label_scheduling_policy.h:25). Returns a reply dict to send
        back (spillback / infeasible), or None to continue with the
        normal local-grant path."""
        from ray_tpu.util.scheduling_strategies import labels_match

        t = strategy.get("type")
        if t == "spread":
            # round-robin over feasible nodes (self included): leases
            # land on distinct nodes regardless of local headroom
            nodes = [{"node_id": self.node_id, "address": None,
                      "labels": self.labels,
                      "resources_available": self.ledger.available}]
            nodes += [n for n in self.cluster_view
                      if n.get("alive", True)
                      and n["node_id"] != self.node_id]
            feasible = [
                n for n in sorted(nodes, key=lambda n: n["node_id"].hex())
                if policy.fits(resources, n.get("resources_available", {}))
            ]
            if not feasible:
                return None  # nothing fits anywhere: queue locally
            self._spread_rr += 1
            chosen = feasible[self._spread_rr % len(feasible)]
            if chosen["address"] is None:  # ourselves
                return None
            # drop_strategy: the target grants locally instead of
            # re-spreading (its own rr counter would ping-pong the lease)
            return {"granted": False, "spill_to": tuple(chosen["address"]),
                    "drop_strategy": True}
        if t == "node_label":
            hard = strategy.get("hard", {})
            soft = strategy.get("soft", {})
            peers = [n for n in self.cluster_view
                     if n.get("alive", True)
                     and n["node_id"] != self.node_id
                     and labels_match(n.get("labels", {}), hard)]
            preferred = [n for n in peers
                         if labels_match(n.get("labels", {}), soft)]
            if labels_match(self.labels, hard):
                if not soft or labels_match(self.labels, soft):
                    return None  # we qualify fully: normal local path
                if preferred:
                    # a peer matches hard AND soft; hand over with
                    # drop_strategy — redirecting with the strategy kept
                    # would let two hard-matching soft-missing nodes
                    # spill the lease to each other forever
                    n = min(preferred, key=lambda n: policy.score(
                        resources, n.get("resources_total", {}),
                        n.get("resources_available", {})))
                    return {"granted": False,
                            "spill_to": tuple(n["address"]),
                            "drop_strategy": True}
                return None  # soft miss everywhere: we still qualify
            pool = preferred or peers
            if pool:
                # local node fails hard: keep the strategy so the target
                # (which matches hard) re-checks and its own resource
                # spillback stays label-constrained
                n = min(pool, key=lambda n: policy.score(
                    resources, n.get("resources_total", {}),
                    n.get("resources_available", {})))
                return {"granted": False, "spill_to": tuple(n["address"])}
            return {"granted": False, "infeasible": True,
                    "error": f"no alive node matches labels {hard}"}
        return None

    def _try_allocate(self, resources, pg_key) -> bool:
        if pg_key is not None:
            return self.ledger.bundle_allocate(pg_key, resources)
        return self.ledger.allocate(resources)

    def _free_resources(self, resources, pg_key):
        if pg_key is not None:
            self.ledger.bundle_free(pg_key, resources)
        else:
            self.ledger.free(resources)

    def _free_lease_resources(self, lease: Lease):
        self._free_resources(lease.resources, lease.pg_key)
        if lease.tpu_chips:
            self._worker_chips.pop(lease.worker.worker_id, None)
            self._release_chips(lease.worker, list(lease.tpu_chips))

    def _release_chips(self, w: WorkerHandle, chips: list):
        """Chips return to the pool only after the worker process actually
        exits — its XLA runtime holds the devices until then."""
        if w.proc.poll() is not None:
            self._tpu_chips_free.extend(chips)
            self._grant_waiters()
            return

        async def wait_exit():
            deadline = time.monotonic() + 5.0
            while w.proc.poll() is None:
                if time.monotonic() > deadline:
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
                await asyncio.sleep(0.05)
            self._tpu_chips_free.extend(chips)
            self._grant_waiters()

        self._bg.spawn(wait_exit())

    def _grant_waiters(self):
        still: list = []
        for resources, fut, pg_key, conn in self._lease_waiters:
            if fut.done() or conn._closed:
                continue  # requester gone: drop without allocating
            if self._try_allocate(resources, pg_key):
                fut.set_result(True)
            else:
                still.append((resources, fut, pg_key, conn))
        self._lease_waiters = still

    def _on_client_disconnect(self, conn):
        self._demand_reports.pop(conn, None)
        for key in [k for k in self._transfer_pins if k[0] is conn]:
            self._release_transfer_pin(conn, key[1])
        for key in [k for k in self._spill_serves if k[0] is conn]:
            self._spill_serve_close(conn, key[1])
        # tunnel lanes bound over this (driver) connection die with it;
        # detach the workers so their lane state frees
        victims = [(lane, ent) for lane, ent in self._tunnel_lanes.items()
                   if ent["client"] is conn]
        by_worker: dict[int, tuple] = {}
        for lane, ent in victims:
            self._tunnel_lanes.pop(lane, None)
            if not ent["wconn"]._closed:
                by_worker.setdefault(id(ent["wconn"]),
                                     (ent["wconn"], []))[1].append(lane)
        self._tunnel_send_grouped(by_worker, "tunnel_detach", "lanes")
        # a failed send means the worker is gone too
        for resources, fut, pg_key, waiter_conn in self._lease_waiters:
            if waiter_conn is conn and not fut.done():
                fut.cancel()
        self._lease_waiters = [w for w in self._lease_waiters if w[3] is not conn]
        # Reclaim *granted* leases whose owner died without return_lease:
        # otherwise the worker and its resources leak forever (ref: raylet
        # disposes of leased workers when the lease owner dies).
        dead = [l for l in self.leases.values() if l.owner_conn is conn]
        for lease in dead:
            self.leases.pop(lease.lease_id, None)
            self._lease_stats["owner_disconnect"] += 1
            self._free_lease_resources(lease)
            w = lease.worker
            w.lease_id = None
            # the worker may be mid-task for a dead owner — terminate rather
            # than recycle (actor workers are single-purpose anyway)
            try:
                w.proc.terminate()
            except OSError:
                pass
            self.all_workers.pop(w.worker_id, None)
            self._release_cgroup_after_exit(w)
        if dead:
            self._grant_waiters()

    def _pick_spillback(self, resources, p):
        """Hybrid-policy spillback: if we can never or not-now satisfy but a
        peer advertises availability, point the client there
        (ref: hybrid_scheduling_policy.h:50, normal_task_submitter.cc:461)."""
        if p.get("no_spill") or p.get("pg_id") is not None:
            return None
        # hard label constraints restrict where resource pressure may
        # spill a lease (ref: node_label_scheduling_policy.h:25)
        hard = None
        strategy = p.get("strategy")
        if strategy and strategy.get("type") == "node_label":
            from ray_tpu.util.scheduling_strategies import labels_match

            hard = strategy.get("hard", {})
        # hybrid top-k among feasible peers (ref: hybrid_scheduling_policy,
        # shared impl in core/policy.py): first-fit would herd every spilled
        # lease from every concurrent client onto the same peer
        scored = []
        for n in self.cluster_view:
            if n["node_id"] == self.node_id or not n.get("alive", True):
                continue
            if hard is not None and not labels_match(
                    n.get("labels", {}), hard):
                continue
            av = n.get("resources_available", {})
            if not policy.fits(resources, av):
                continue
            scored.append((
                policy.score(resources, n.get("resources_total", {}), av),
                tuple(n["address"]),
            ))
        return policy.pick(scored)

    async def rpc_return_lease(self, conn, p):
        lease = self.leases.pop(p["lease_id"], None)
        if lease is None:
            return False
        self._lease_stats["returned"] += 1
        self._free_lease_resources(lease)
        w = lease.worker
        w.lease_id = None
        if p.get("kill") or w.actor_id is not None or lease.tpu_chips:
            # TPU workers are single-assignment: the XLA runtime pinned its
            # chip set at first init, so recycling would leak the old chips
            w.proc.terminate()
            self.all_workers.pop(w.worker_id, None)
            self._release_cgroup_after_exit(w)
        elif w.proc.poll() is None:
            w.idle_since = time.monotonic()
            self.idle_workers.append(w)
        self._grant_waiters()
        return True

    # ----------------------------------------------------- placement bundles
    async def rpc_prepare_bundle(self, conn, p):
        key = (p["pg_id"], p["bundle_index"])
        return {"ok": self.ledger.prepare_bundle(key, p["resources"])}

    async def rpc_commit_bundle(self, conn, p):
        return {"ok": self.ledger.commit_bundle((p["pg_id"], p["bundle_index"]))}

    async def rpc_prepare_bundles(self, conn, p):
        """Batched 2PC phase 1 (protocol 2.0): every bundle this node
        hosts for one PG reserves in a single ledger pass — one RPC per
        node per phase instead of one per bundle. Per-bundle outcomes so
        the GCS repairs exactly what failed."""
        return [{"ok": self.ledger.prepare_bundle((p["pg_id"], idx), res)}
                for idx, res in p["bundles"]]

    async def rpc_commit_bundles(self, conn, p):
        """Batched 2PC phase 2 — the commit twin of prepare_bundles."""
        return [{"ok": self.ledger.commit_bundle((p["pg_id"], idx))}
                for idx in p["indices"]]

    async def rpc_return_bundle(self, conn, p):
        self.ledger.return_bundle((p["pg_id"], p["bundle_index"]))
        self._grant_waiters()
        return {"ok": True}

    async def rpc_list_bundles(self, conn, p):
        """Bundle reservations this node's ledger holds (the PG
        fault-tolerance audit surface: the churn harness and tests
        assert zero leaked reservations here after settle)."""
        return self._held_bundles()

    def _held_bundles(self) -> list[dict]:
        return self.ledger.held_bundles()

    def _gc_stale_bundles(self, now: float) -> None:
        """Reclaim expired prepared-uncommitted reservations (the sweep
        behind the bundle-lease semantics — without it a GCS crash
        between prepare and commit leaks the capacity forever)."""
        stale = self.ledger.gc_stale_bundles(
            now, getattr(self.cfg, "pg_bundle_lease_s", 30.0))
        for key in stale:
            log.warning(
                "returned stale prepared bundle %s (no commit within the "
                "lease: 2PC coordinator lost)", key)
        if stale:
            self._grant_waiters()

    async def rpc_report_demand(self, conn, p):
        """Client backlog report: tasks queued driver-side (including shm
        fast-path rings) that no live lease can absorb. Feeds the
        autoscaler via the heartbeat demand signal (ref: autoscaler v2
        resource-demand reporting). Latest report per client wins."""
        count = int(p.get("count", 0))
        if count <= 0:
            self._demand_reports.pop(conn, None)
        else:
            self._demand_reports[conn] = count
        return True

    # -------------------------------------------------------- object plane
    async def rpc_register_client(self, conn, p):
        """Drivers/workers on this node discover the store + node identity."""
        return {
            "node_id": self.node_id,
            "store_name": self.store_name,
            "address": self.server.address,
            "resources_total": self.ledger.total,
        }

    async def rpc_delete_object(self, conn, p):
        """Owner-driven release of this node's sealed copy (the reference's
        free-objects batch, local_object_manager.h). A copy with live
        reader refs only gets LRU-demoted by the native delete, so retry
        until the readers drop and the bytes actually free."""
        oid = ObjectID(p["object_id"])
        self._drop_spill_file(oid)  # freed objects don't keep disk copies

        async def drain():
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                try:
                    self.store.delete(oid)
                except Exception:
                    return
                if not self.store.contains(oid):
                    return
                await asyncio.sleep(0.25)

        if p.get("wait"):
            await drain()  # tests / synchronous callers
        else:
            self._bg.spawn(drain())
        return True

    # ----------------------------------------------------- object spilling
    # (ref: local_object_manager.h:42 SpillObjects/RestoreSpilledObject:
    # sealed objects move to disk under arena pressure; pulls and peer
    # fetches restore them on demand. The node stays listed as a holder in
    # the GCS directory — it can always materialize the bytes.)

    async def _spill_monitor_loop(self):
        while not self._stopping:
            try:
                # watermark first: the spill trigger reads the recent
                # PEAK (1s of history) instead of whatever instant this
                # tick sampled — a burst that allocated and briefly
                # dipped still crosses the threshold
                self._store_watermark.note(self.store.bytes_in_use)
                peak = self._store_watermark.recent_peak(1.0)
                usage = peak / max(1, self.store.capacity)
                if usage >= self.cfg.object_spilling_threshold:
                    await self._spill_until_low_water()
                await self._publish_raylet_metrics()
            except Exception:
                if self._stopping:  # executor torn down mid-pass
                    return
                traceback.print_exc()
            await asyncio.sleep(0.2)

    async def _publish_raylet_metrics(self):
        """~1/s hand-rolled snapshot into ns="metrics" (key
        raylet.<node>): object-store watermarks + lease lifecycle
        counters. Hand-rolled cells, NOT the process registry — the
        in-process topology shares that registry with the driver whose
        flush already publishes it (see _trace_metrics_tick in gcs.py
        for the same idiom)."""
        now = time.monotonic()
        if now - self._metrics_published_at < 1.0 or self.gcs is None:
            return
        self._metrics_published_at = now
        wm = self._store_watermark
        tags = {"arena": "object_store"}
        snap = {"metrics": {
            "rt_arena_bytes": {"type": "gauge", "samples": [
                {"tags": tags, "value": float(wm.live)}]},
            "rt_arena_peak_bytes": {"type": "gauge", "samples": [
                {"tags": tags, "value": float(wm.peak)}]},
            "rt_arena_capacity_bytes": {"type": "gauge", "samples": [
                {"tags": tags, "value": float(self.store.capacity)}]},
            "rt_leases_active": {"type": "gauge", "samples": [
                {"tags": {}, "value": float(len(self.leases))}]},
            "rt_lease_events_total": {"type": "counter", "samples": [
                {"tags": {"event": k}, "value": float(v)}
                for k, v in self._lease_stats.items()]},
        }}
        try:
            await self.gcs.call("kv_put", {
                "ns": "metrics", "key": f"raylet.{self.node_id.hex()}",
                "value": pickle.dumps(snap)})
        except Exception:
            log.debug("raylet metrics publish failed", exc_info=True)

    async def rpc_spill_now(self, conn, p):
        """Synchronous spill pass — pressured putters call this before a
        large create so the arena frees by SPILL (bytes preserved on disk)
        rather than by LRU eviction (bytes destroyed, lineage recompute)."""
        need = int(p.get("need", 0))
        await self._spill_until_low_water(extra_need=need)
        return True

    async def _spill_until_low_water(self, extra_need: int = 0):
        async with self._spill_lock:
            cap = max(1, self.store.capacity)
            target = int(self.cfg.object_spilling_low_water * cap) - extra_need
            loop = asyncio.get_running_loop()
            now = time.monotonic()
            while self.store.bytes_in_use > target:
                cands = [
                    (oid, sz)
                    for oid, sz in self.store.list_spillable(64)
                    # skip candidates whose spill recently failed (full
                    # disk etc.), with per-oid exponential backoff so the
                    # monitor doesn't hot-loop on a bad disk
                    if now - self._spill_failed_at.get(oid, -1e9)
                    >= self._spill_backoff_s(oid)
                ]
                if not cands:
                    break
                for oid, _sz in cands:
                    if self.store.bytes_in_use <= target:
                        return
                    await loop.run_in_executor(None, self._spill_one, oid)
            if self.store.bytes_in_use > target:
                # unreferenced candidates exhausted: ask registered arena
                # owners (prefix cache, shard plane, staging trackers) to
                # trade cold REFERENCED pages to tier-1
                await self._cooperative_spill(
                    self.store.bytes_in_use - target, loop)

    def _spill_backoff_s(self, oid: ObjectID) -> float:
        n = self._spill_fail_n.get(oid, 0)
        return 0.0 if n == 0 else min(60.0, 0.5 * (2 ** (n - 1)))

    def _note_spill_failure(self, oid: ObjectID):
        self._spill_failed_at[oid] = time.monotonic()
        self._spill_fail_n[oid] = self._spill_fail_n.get(oid, 0) + 1
        self.store.note_spill_failure()

    async def rpc_register_spill_provider(self, conn, p):
        """A local client process declares it can serve cold arena-owner
        spill candidates (core/tiering.py registry) at this RPC address."""
        self._spill_providers.add(tuple(p["address"]))
        return True

    async def _provider_conn(self, addr: tuple):
        conn = self._provider_conns.get(addr)
        if conn is not None and not conn._closed:
            return conn
        try:
            conn = await rpc.connect(*addr, timeout=2.0)
        except Exception:
            self._spill_providers.discard(addr)
            self._provider_conns.pop(addr, None)
            return None
        self._provider_conns[addr] = conn
        return conn

    async def _cooperative_spill(self, need: int, loop):
        """Ask each registered provider for cold referenced candidates and
        spill them; report the landed (oid, path) pairs back so owners can
        stamp manifest tier legs. Runs under _spill_lock (caller holds)."""
        for addr in sorted(self._spill_providers):
            conn = await self._provider_conn(addr)
            if conn is None:
                continue
            try:
                cands = await conn.call(
                    "arena_spill_candidates",
                    {"need": int(need),
                     "cold_after_s": self.cfg.spill_cold_after_s},
                    timeout=2.0)
            except (rpc.RpcError, OSError):
                self._spill_providers.discard(addr)
                self._provider_conns.pop(addr, None)
                continue
            spilled = []
            for item in cands or ():
                oid = ObjectID(item["object_id"])
                if not self.store.contains(oid):
                    continue
                await loop.run_in_executor(None, self._spill_one, oid)
                path = self._spilled.get(oid)
                if path is not None and not self.store.contains(oid):
                    spilled.append({"object_id": oid.binary(), "path": path})
                    need -= int(item.get("nbytes", 0))
            if spilled:
                try:
                    await conn.call("arena_spilled", {"spilled": spilled},
                                    timeout=2.0)
                except (rpc.RpcError, OSError):
                    pass  # owner gone; its refs will free the files
            if need <= 0:
                return

    async def rpc_spill_objects(self, conn, p):
        """Explicit spill of specific sealed objects — the owner-initiated
        leg of cooperative tiering (e.g. the prefix cache's spill-not-drop
        eviction trades its own cold pages for headroom without waiting
        for the monitor). Returns {oid hex: {"ok", "path"}}."""
        loop = asyncio.get_running_loop()
        out: dict[str, dict] = {}
        async with self._spill_lock:
            for raw in p.get("object_ids") or ():
                oid = ObjectID(raw)
                have = self.store.contains(oid)
                if not have and oid in self._spilled:
                    out[oid.hex()] = {"ok": True, "path": self._spilled[oid]}
                    continue
                if not have:
                    out[oid.hex()] = {"ok": False, "path": ""}
                    continue
                await loop.run_in_executor(None, self._spill_one, oid)
                path = self._spilled.get(oid)
                ok = path is not None and not self.store.contains(oid)
                out[oid.hex()] = {"ok": bool(ok), "path": path or ""}
        return out

    def _spill_one(self, oid: ObjectID):
        """Move one sealed object's bytes out of the arena. Runs off-loop
        (disk IO). A previously-spilled object whose file is still valid
        (restore keeps it) skips the write — dropping the arena copy is
        enough. Safe vs concurrent gets: the buffer ref pins the bytes
        while copying; after delete, readers miss and take the pull path
        which restores from disk."""
        with self._spill_state_lock:
            self._spilling_now.add(oid)
        try:
            path = self._spilled.get(oid)
            if path is None or not os.path.exists(path):
                act = None
                if chaos.ENABLED:
                    # "store.spill" fault point (phase=write): error acts
                    # like a failed disk write (backoff + counter), drop
                    # means the file was lost after the write, delay
                    # widens the mid-spill window
                    try:
                        act = chaos.point("store.spill", oid=oid.hex(),
                                          phase="write")
                    except chaos.ChaosError:
                        self._note_spill_failure(oid)
                        return
                try:
                    buf = self.store.get_buffer(oid, timeout_ms=0)
                except ObjectStoreError:
                    return  # raced an eviction/delete: nothing to spill
                nbytes = len(buf)
                path = os.path.join(self.spill_dir, oid.hex())
                tmp = path + ".tmp"
                try:
                    os.makedirs(self.spill_dir, exist_ok=True)
                    with open(tmp, "wb") as f:
                        f.write(buf)
                    os.replace(tmp, path)
                except OSError:
                    # disk full / unwritable: remember (with exponential
                    # backoff) and move on
                    self._note_spill_failure(oid)
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
                    return
                finally:
                    self.store.release(oid)
                if act is not None and act.kind == "drop":
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    self._note_spill_failure(oid)
                    return
                self._spilled[oid] = path
                self._spill_failed_at.pop(oid, None)
                self._spill_fail_n.pop(oid, None)
                metrics.objects_spilled.inc()
                metrics.spill_bytes_total.inc(nbytes)
            self.store.delete(oid)
        finally:
            with self._spill_state_lock:
                self._spilling_now.discard(oid)
                freed = oid in self._freed_while_spilling
                self._freed_while_spilling.discard(oid)
            if freed:
                self._drop_spill_file(oid)

    def _restore_spilled(self, oid: ObjectID) -> bool:
        """Disk -> arena (blocking; call off-loop): one sequential read
        straight into a fresh arena create, then seal — no intermediate
        heap copy. Leaves the file in place until the object is freed, so
        repeated pressure cycles re-spill without rewriting unchanged
        bytes."""
        path = self._spilled.get(oid)
        if path is None:
            return False
        if chaos.ENABLED:
            # "store.restore" fault point (phase=read): error/drop act
            # like an unreadable tier-1 file (this attempt fails; the
            # puller falls back / retries), delay models slow disk
            try:
                act = chaos.point("store.restore", oid=oid.hex(),
                                  phase="read")
            except chaos.ChaosError:
                return False
            if act is not None and act.kind == "drop":
                return False
        try:
            size = os.path.getsize(path)
        except OSError:
            self._spilled.pop(oid, None)
            return False
        try:
            buf = self.store.create(oid, size)
        except ObjectStoreError:
            return self.store.contains(oid)  # raced another restore
        ok = False
        try:
            with open(path, "rb") as f:
                ok = f.readinto(buf) == size
        except OSError:
            ok = False
        finally:
            del buf
            if ok:
                try:
                    self.store.seal(oid)
                except ObjectStoreError:
                    ok = False
            if not ok:
                try:
                    self.store.delete(oid)  # abort the half-create
                except ObjectStoreError:
                    pass
        if not ok:
            return False
        metrics.objects_restored.inc()
        metrics.restore_bytes_total.inc(size)
        return True

    def _drop_spill_file(self, oid: ObjectID):
        with self._spill_state_lock:
            if oid in self._spilling_now:
                # a spill is writing this object's file right now; the
                # spill's finally will see the marker and drop the file
                self._freed_while_spilling.add(oid)
                return
            self._spill_failed_at.pop(oid, None)
            self._spill_fail_n.pop(oid, None)
            path = self._spilled.pop(oid, None)
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass

    # -------------------------------------------- cross-node DAG channels
    # (the RegisterMutableObjectReader role, ref: core_worker.proto:577 +
    # experimental_mutable_object_provider.cc: remote readers of a mutable
    # object get a local mirror cell fed one push per version)

    async def rpc_channel_create(self, conn, p):
        """Create a channel cell (origin or mirror) in this node's arena."""
        cid = ObjectID(p["chan_id"])
        self.store.channel_create(cid, int(p["size"]), int(p["num_readers"]))
        return True

    async def rpc_channel_push(self, conn, p):
        """Write one version's packed payload into a local mirror cell.
        Blocks (off-loop) until the mirror's readers released the previous
        version — backpressure propagates across the network."""
        cid = ObjectID(p["chan_id"])
        payload = p["payload"]

        def push():
            buf = self.store.channel_write_acquire(cid, -1)
            buf[: len(payload)] = payload
            self.store.channel_write_release(cid, len(payload))

        await asyncio.get_running_loop().run_in_executor(
            self._chan_io_executor(cid), push)
        return True

    async def rpc_channel_register_remote(self, conn, p):
        """Start a forwarder pumping this node's channel cell to mirror
        cells on remote nodes, one push per version, releasing the origin
        only after every mirror accepted (keeps the end-to-end depth-1
        write/read protocol of the shm cells)."""
        cid = ObjectID(p["chan_id"])
        targets = [tuple(a) for a in p["readers"]]
        self._bg.spawn(self._channel_forwarder(cid, targets))
        return True

    async def rpc_channel_close(self, conn, p):
        cid = ObjectID(p["chan_id"])
        try:
            self.store.channel_close(cid)
        except Exception:
            log.debug("channel close failed", exc_info=True)
        # mirror nodes create a push executor per channel: release it here
        # (the forwarder's finally only runs on the origin node)
        ex = getattr(self, "_chan_execs", {}).pop(cid, None)
        if ex is not None:
            ex.shutdown(wait=False)
        return True

    def _chan_io_executor(self, cid: ObjectID):
        """One single-thread executor per channel: blocking cell waits must
        not starve the shared pool (a parked forwarder would otherwise hold
        a shared worker thread for the DAG's lifetime)."""
        if not hasattr(self, "_chan_execs"):
            self._chan_execs = {}
        ex = self._chan_execs.get(cid)
        if ex is None:
            import concurrent.futures as _cf

            ex = self._chan_execs[cid] = _cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"rt-chan-{cid.hex()[:8]}")
        return ex

    async def _channel_forwarder(self, cid: ObjectID, targets: list):
        from ray_tpu.core.object_store import ChannelClosedError

        loop = asyncio.get_running_loop()
        ex = self._chan_io_executor(cid)
        conns = []
        try:
            for t in targets:
                conns.append(await rpc.connect(
                    *t, timeout=self.cfg.rpc_connect_timeout_s))
            last_version = 0

            def read_next(v=None):
                return self.store.channel_read_acquire(cid, last_version, -1)

            while True:
                payload, version = await loop.run_in_executor(ex, read_next)
                data = bytes(payload)
                await asyncio.gather(*[
                    c.call("channel_push",
                           {"chan_id": cid.binary(), "payload": data},
                           timeout=None)
                    for c in conns
                ])
                self.store.channel_read_release(cid)
                last_version = version
        except ChannelClosedError:
            pass  # normal teardown: origin closed under us
        except Exception:
            # a mirror died or the forwarder itself broke: this is NOT a
            # clean close — log it, or the DAG just stops delivering
            # versions with zero diagnostics
            traceback.print_exc()
        finally:
            # propagate the close both ways: mirrors stop their readers,
            # and the ORIGIN cell closes so the producer's next write
            # raises ChannelClosed instead of blocking forever on the
            # never-released read slot
            for c in conns:
                try:
                    await c.call("channel_close", {"chan_id": cid.binary()},
                                 timeout=5)
                except Exception:
                    log.debug("mirror channel_close failed", exc_info=True)
            try:
                self.store.channel_close(cid)
            except Exception:
                log.debug("origin channel close failed", exc_info=True)
            for c in conns:
                try:
                    await c.close()
                except (rpc.RpcError, OSError):
                    pass  # reader link already dead
            ex2 = getattr(self, "_chan_execs", {}).pop(cid, None)
            if ex2 is not None:
                ex2.shutdown(wait=False)

    # --------------------------------------------- node tunnel (core/tunnel.py)
    def _find_tunnel_worker(self, p) -> "WorkerHandle | None":
        """Resolve a bind target: explicit worker id, or the worker
        hosting the named actor (actor leases stamp w.actor_id)."""
        wid = p.get("worker_id")
        if wid is not None:
            return self.all_workers.get(WorkerID.from_hex(wid))
        aid = p.get("actor_id")
        if aid is None:
            return None
        for w in self.all_workers.values():
            wa = w.actor_id
            if wa is None:
                continue
            wa_hex = wa.hex() if hasattr(wa, "hex") else str(wa)
            if wa_hex == aid:
                return w
        return None

    async def _tunnel_worker_conn(self, w: "WorkerHandle"):
        """Cached persistent raylet->worker connection for tunnel
        traffic (one per worker, shared by every lane bound on it)."""
        conn = self._tunnel_worker_conns.get(w.worker_id)
        if conn is not None and not conn._closed:
            return conn
        conn = await rpc.connect(*w.address, timeout=5)
        conn.on_message = self._on_tunnel_worker_push
        self._tunnel_worker_conns[w.worker_id] = conn
        return conn

    async def rpc_tunnel_bind(self, conn, p):
        """Bind one tunnel lane: remote driver -> (this raylet) -> local
        worker (protocol 2.0). The reply carries the raylet-assigned lane
        id and, for actor lanes, the worker's method eligibility table.
        The lane lives until the driver detaches, the driver's tunnel
        connection drops, or the worker dies (-> tunnel_down push)."""
        w = self._find_tunnel_worker(p)
        if w is None or w.address is None or w.proc.poll() is not None:
            return {"ok": False, "error": "no such worker"}
        try:
            wconn = await self._tunnel_worker_conn(w)
            lane = next(self._tunnel_ids)
            reply = await wconn.call(
                "tunnel_attach", {"lane": lane, "kind": p.get("kind", "task")},
                timeout=10)
        except (rpc.RpcError, OSError, asyncio.TimeoutError):
            return {"ok": False, "error": "worker unreachable"}
        if not isinstance(reply, dict) or not reply.get("ok"):
            return {"ok": False, "error": "worker refused"}
        self._tunnel_lanes[lane] = {
            "client": conn, "worker": w.worker_id, "wconn": wconn,
        }
        return {"ok": True, "lane": lane, "methods": reply.get("methods")}

    @staticmethod
    def _tunnel_send_grouped(groups: dict, method: str, key: str) -> list:
        """One tunnel notify per connection. ``groups``: id(conn) ->
        (conn, items); the payload is ``{key: items}``. Returns the
        items of every connection whose send failed (dead link) so the
        caller can reap/bounce exactly those — the one shared shape
        behind every tunnel fan-out below."""
        failed: list = []
        for conn, items in groups.values():
            try:
                conn.send_nowait({"k": "n", "m": method, "p": {key: items}})
            except (rpc.ConnectionLost, OSError):
                failed.extend(items)
        return failed

    async def rpc_tunnel_frame(self, conn, p):
        """Forward one driver frame's per-lane record chunks to their
        workers (notify; no reply). Forwarding is synchronous within the
        handler so frame order per lane is preserved end to end —
        dispatch order is the caller's FIFO invariant. Lanes this raylet
        does not know (worker died, stale bind) bounce back as a
        tunnel_down push so the driver breaks exactly those lanes."""
        by_worker: dict[int, tuple] = {}
        dead: list = []
        for lane, recs in p["frames"]:
            ent = self._tunnel_lanes.get(lane)
            if ent is None or ent["client"] is not conn:
                dead.append(lane)
                continue
            wconn = ent["wconn"]
            if wconn._closed:
                dead.append(lane)
                self._tunnel_lanes.pop(lane, None)
                continue
            by_worker.setdefault(id(wconn), (wconn, []))[1].append(
                (lane, recs))
        for lane, _ in self._tunnel_send_grouped(
                by_worker, "tunnel_records", "frames"):
            dead.append(lane)
            self._tunnel_lanes.pop(lane, None)
        if dead:
            self._tunnel_send_grouped(
                {0: (conn, dead)}, "tunnel_down", "lanes")
            # driver gone too: its health sweep owns the break

    async def rpc_tunnel_detach(self, conn, p):
        """Driver closed lanes (notify): reap routing entries and tell
        the workers so their lane state frees."""
        by_worker: dict[int, tuple] = {}
        for lane in p.get("lanes", ()):
            ent = self._tunnel_lanes.pop(lane, None)
            if ent is None or ent["wconn"]._closed:
                continue
            by_worker.setdefault(id(ent["wconn"]),
                                 (ent["wconn"], []))[1].append(lane)
        self._tunnel_send_grouped(by_worker, "tunnel_detach", "lanes")
        # a failed send means the worker is gone: lane state died with it

    def _on_tunnel_worker_push(self, msg):
        """Reply frames from a worker: forward each lane's records to
        the driver that bound the lane, coalesced per client connection."""
        if msg.get("m") != "tunnel_replies":
            return
        by_client: dict[int, tuple] = {}
        for lane, recs in msg["p"]["frames"]:
            ent = self._tunnel_lanes.get(lane)
            if ent is None:
                continue
            by_client.setdefault(id(ent["client"]),
                                 (ent["client"], []))[1].append((lane, recs))
        for lane, _ in self._tunnel_send_grouped(
                by_client, "tunnel_frame", "frames"):
            # driver gone: drop its lanes; workers are detached by the
            # disconnect sweep
            self._tunnel_lanes.pop(lane, None)

    def _reap_tunnel_lanes_for_worker(self, worker_id: WorkerID):
        """Worker died: push tunnel_down for its lanes so every bound
        driver breaks them (per-call RPC fallback + revival later)."""
        self._tunnel_worker_conns.pop(worker_id, None)
        victims = [(lane, ent) for lane, ent in self._tunnel_lanes.items()
                   if ent["worker"] == worker_id]
        by_client: dict[int, tuple] = {}
        for lane, ent in victims:
            self._tunnel_lanes.pop(lane, None)
            by_client.setdefault(id(ent["client"]),
                                 (ent["client"], []))[1].append(lane)
        self._tunnel_send_grouped(by_client, "tunnel_down", "lanes")
        # a failed send means the driver is gone: nothing left to tell

    async def rpc_pull_objects(self, conn, p):
        """Batched multi-object pull (protocol 2.0): one round trip
        fetches a whole arg/KV-manifest set into the local store. Hinted
        objects skip the directory entirely; the UNHINTED miss-set costs
        exactly ONE ``kv_multi_get`` (not one directory lookup per oid —
        PR 3's completion-time priming, extended to the raylet path).
        Each inbound transfer/restore is byte-admitted through the
        PullAdmission window (items may carry an ``nbytes`` estimate; the
        payload may carry ``timeout_s`` as the admission deadline). A
        shed item reports its retry hint under the ``"_bp"`` key, and
        items restored from tier-1 list their hexes under ``"_restored"``
        (both safe beside the 40-char oid-hex keys).

        Returns {oid hex: bool} plus the side-channel keys."""
        out: dict = {}
        todo: list = []
        for item in p["objects"]:
            oid = ObjectID(item["object_id"])
            if self.store.contains(oid):
                out[oid.hex()] = True
                continue
            todo.append((oid, set(item.get("holders_hint") or ()),
                         int(item.get("nbytes") or 0)))
        if not todo:
            return out
        deadline = None
        if p.get("timeout_s") is not None:
            deadline = time.monotonic() + float(p["timeout_s"])
        no_hint = [oid for oid, hint, _n in todo if not hint]
        primed: dict[ObjectID, set] = {}
        if no_hint:
            try:
                blobs = await self.gcs.call(
                    "kv_multi_get",
                    {"ns": "obj_loc", "keys": [o.hex() for o in no_hint]})
            except (rpc.RpcError, OSError):
                blobs = None
            for oid in no_hint:
                blob = (blobs or {}).get(oid.hex())
                if blob:
                    try:
                        primed[oid] = set(pickle.loads(blob))
                    except (pickle.UnpicklingError, TypeError, EOFError):
                        pass  # torn directory blob: a cache miss

        restored: list[str] = []
        bp: dict[str, float] = {}

        async def one(oid: ObjectID, hint: set, nbytes: int) -> bool:
            holders = hint | primed.get(oid, set())
            was_spilled = oid in self._spilled
            if not holders and not was_spilled:
                return False  # nowhere to pull from, nothing spilled
            est = (nbytes or self._spilled_size(oid)
                   or self.cfg.object_transfer_chunk_size)
            try:
                await self._pull_admission.acquire(est, deadline)
            except PullBackPressure as e:
                bp[oid.hex()] = e.retry_after_s
                return False
            try:
                ok = await self._pull_one_dedup(oid, sorted(holders))
            finally:
                self._pull_admission.release(est)
            if ok and was_spilled:
                restored.append(oid.hex())
            return ok

        results = await asyncio.gather(
            *(one(oid, hint, n) for oid, hint, n in todo),
            return_exceptions=True)
        for (oid, _h, _n), ok in zip(todo, results):
            out[oid.hex()] = ok is True
        if restored:
            out["_restored"] = restored
        if bp:
            out["_bp"] = bp
        return out

    async def rpc_pull_object(self, conn, p):
        """Pull an object into the local store from whichever node holds it.
        The caller may pass ``holders_hint`` (node ids from its
        completion-time location cache): hinted nodes are tried first
        WITHOUT consulting the GCS object directory — zero directory
        round-trips in steady state — and a stale hint falls back to the
        directory, which stays the source of truth. Concurrent pulls of
        the same object coalesce onto one transfer (ref: pull_manager.h:49
        request dedup + admission control)."""
        oid = ObjectID(p["object_id"])
        if self.store.contains(oid):
            return True
        est = self._spilled_size(oid) or self.cfg.object_transfer_chunk_size
        try:
            # single-object gets keep wait-then-succeed semantics: a long
            # default deadline parks them through bursts instead of
            # shedding (the shed path belongs to batched adoptions)
            await self._pull_admission.acquire(est)
        except PullBackPressure:
            return False
        try:
            return await self._pull_one_dedup(oid, p.get("holders_hint"))
        finally:
            self._pull_admission.release(est)

    def _spilled_size(self, oid: ObjectID) -> int:
        path = self._spilled.get(oid)
        if path is None:
            return 0
        try:
            return os.path.getsize(path)
        except OSError:
            return 0

    async def _pull_one_dedup(self, oid: ObjectID, holders_hint=None) -> bool:
        """Dedup'd single-object pull: concurrent pulls of the same oid
        (including batch-mates from pull_objects) coalesce onto one
        transfer."""
        if self.store.contains(oid):
            return True
        if oid in self._spilled:  # restore beats a network pull
            if await self._ensure_local_bytes(oid):
                return True
        fut = self._active_pulls.get(oid)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._active_pulls[oid] = fut
        try:
            ok = await self._pull_object(oid, holders_hint)
            fut.set_result(ok)
            return ok
        except Exception as e:
            fut.set_result(False)
            raise e
        finally:
            self._active_pulls.pop(oid, None)

    async def _pull_object(self, oid: ObjectID, holders_hint=None) -> bool:
        if holders_hint:
            if await self._pull_from_holders(oid, set(holders_hint),
                                             register=True):
                return True
            # hint was stale (holder died / copy evicted): directory path
        locs = await self.gcs.call("kv_get", {"ns": "obj_loc", "key": oid.hex()})
        if not locs:
            return False
        import pickle as _p

        holders = _p.loads(locs)
        return await self._pull_from_holders(oid, holders, register=True)

    async def _pull_from_holders(self, oid: ObjectID, holders: set,
                                 register: bool) -> bool:
        import pickle as _p

        for node in self.cluster_view:
            if node["node_id"].binary() in holders and node["node_id"] != self.node_id:
                # byte-budget admission happened at the pull entry point
                # (rpc_pull_object/rpc_pull_objects), so the transfer
                # itself runs unthrottled here
                try:
                    if await self._chunked_fetch(oid, tuple(node["address"])):
                        if register:
                            # read-modify-write the directory so later
                            # pulls (and the owner's free) see this copy
                            locs = await self.gcs.call(
                                "kv_get",
                                {"ns": "obj_loc", "key": oid.hex()})
                            merged = _p.loads(locs) if locs else set()
                            merged.add(self.node_id.binary())
                            await self.gcs.call(
                                "kv_put",
                                {"ns": "obj_loc", "key": oid.hex(),
                                 "value": _p.dumps(merged)},
                            )
                        return True
                except Exception:
                    continue
        return False

    async def _chunked_fetch(self, oid: ObjectID, address: tuple) -> bool:
        """Stream an object in bounded chunks straight into local shm —
        peak transient memory is chunk_size x window, independent of object
        size (ref: push_manager.h:28 chunked pushes,
        chunk_object_reader.cc)."""
        chunk = self.cfg.object_transfer_chunk_size
        window = 4  # in-flight chunk requests (pipelined)
        c = await rpc.connect(*address, timeout=self.cfg.rpc_connect_timeout_s)
        pinned = False
        try:
            meta = await c.call("fetch_object_meta", {"object_id": oid.binary()},
                                timeout=self.cfg.rpc_connect_timeout_s)
            if not meta:
                return False
            pinned = True  # holder keeps a store ref until fetch_object_done
            size = meta["size"]
            if self.store.contains(oid):
                return True
            if size <= chunk:
                raw = await c.call("fetch_object", {"object_id": oid.binary()},
                                   timeout=self.cfg.rpc_connect_timeout_s)
                if raw is None:
                    return False
                self.store.put_raw(oid, raw)
                return True
            buf = self.store.create(oid, size)
            try:
                # true sliding window: `window` chunk requests always in
                # flight (a barriered gather per batch would idle the link
                # for a full RTT between batches)
                sem = asyncio.Semaphore(window)

                async def fetch_one(off: int):
                    async with sem:
                        part = await c.call(
                            "fetch_object_chunk",
                            {"object_id": oid.binary(), "offset": off,
                             "length": min(chunk, size - off)},
                            timeout=self.cfg.rpc_connect_timeout_s,
                        )
                    if part is None:
                        raise rpc.RpcError(f"holder lost {oid} mid-transfer")
                    buf[off : off + len(part)] = part

                await asyncio.gather(
                    *(fetch_one(off) for off in range(0, size, chunk))
                )
                self.store.seal(oid)
                return True
            except Exception:
                try:  # abort the half-written create so the slot isn't stuck
                    self.store.delete(oid)
                except ObjectStoreError:
                    pass  # nothing to abort (create itself failed)
                raise
        finally:
            if pinned:
                try:
                    await c.notify("fetch_object_done", {"object_id": oid.binary()})
                except (rpc.RpcError, OSError):
                    pass  # holder gone: its pin died with it
            await c.close()

    async def _ensure_local_bytes(self, oid: ObjectID) -> bool:
        """Restore a spilled object into the arena if needed (peer fetches
        and local pulls both land here before touching the store).

        Spills FIRST when the restore wouldn't fit below the pressure
        threshold: a restore-triggered eviction could otherwise destroy a
        resident object that has no disk copy yet."""
        if self.store.contains(oid):
            return True
        path = self._spilled.get(oid)
        if path is None:
            return False
        try:
            need = os.path.getsize(path)
        except OSError:
            need = 0
        cap = max(1, self.store.capacity)
        loop = asyncio.get_running_loop()
        # retry across transient full-arena conditions: the bytes exist on
        # disk, so "arena fully pinned by reader views right now" must wait
        # for releases, not surface as object-lost
        deadline = time.monotonic() + 30.0
        while True:
            if self.store.bytes_in_use + need > self.cfg.object_spilling_threshold * cap:
                await self._spill_until_low_water(extra_need=need)
            if await loop.run_in_executor(None, self._restore_spilled, oid):
                return True
            if oid not in self._spilled or time.monotonic() > deadline:
                return False
            await asyncio.sleep(0.2)

    def _spill_serve_open(self, conn, oid: ObjectID):
        """Open (and cache per (conn, oid)) this object's tier-1 file for
        peer serving. The held fd plays the transfer pin's role: a
        concurrent free/unlink can't tear the chunked stream, the kernel
        keeps the inode until fetch_object_done closes it."""
        key = (conn, oid)
        ent = self._spill_serves.get(key)
        if ent is not None:
            return ent
        if self.store.contains(oid):
            return None  # shm copy wins: serve zero-copy from the arena
        path = self._spilled.get(oid)
        if path is None:
            return None
        try:
            f = open(path, "rb")
        except OSError:
            return None
        ent = (f, os.fstat(f.fileno()).st_size)
        self._spill_serves[key] = ent
        return ent

    def _spill_serve_close(self, conn, oid: ObjectID):
        ent = self._spill_serves.pop((conn, oid), None)
        if ent is not None:
            try:
                ent[0].close()
            except OSError:
                pass

    async def rpc_fetch_object_meta(self, conn, p):
        """Start of a transfer: pin the object (one store ref held for the
        whole transfer so eviction/owner-delete can't yank it mid-stream);
        the peer releases via fetch_object_done or by disconnecting. A
        spilled object serves straight from its tier-1 file — no restore
        into (so no pressure on) this node's arena; the open fd is the
        pin."""
        oid = ObjectID(p["object_id"])
        ent = self._spill_serve_open(conn, oid)
        if ent is not None:
            return {"size": ent[1]}
        try:
            buf = self.store.get_buffer(oid, timeout_ms=0)
        except Exception:
            return None
        size = len(buf)
        del buf
        key = (conn, oid)
        if key in self._transfer_pins:
            self.store.release(oid)  # already pinned by this peer
        else:
            self._transfer_pins[key] = True
        return {"size": size}

    def _release_transfer_pin(self, conn, oid: ObjectID):
        self._spill_serve_close(conn, oid)
        if self._transfer_pins.pop((conn, oid), None):
            try:
                self.store.release(oid)
            except ObjectStoreError:
                pass  # already deleted/evicted: the pin is moot

    async def rpc_fetch_object_done(self, conn, p):
        self._release_transfer_pin(conn, ObjectID(p["object_id"]))
        return True

    async def rpc_fetch_object_chunk(self, conn, p):
        oid = ObjectID(p["object_id"])
        off, length = p["offset"], p["length"]
        ent = self._spill_serve_open(conn, oid)
        if ent is not None:
            loop = asyncio.get_running_loop()
            try:
                return await loop.run_in_executor(
                    None, os.pread, ent[0].fileno(), length, off)
            except OSError:
                return None
        await self._ensure_local_bytes(oid)
        try:
            buf = self.store.get_buffer(oid, timeout_ms=0)
        except Exception:
            return None
        try:
            return bytes(buf[off : off + length])
        finally:
            del buf
            self.store.release(oid)

    async def rpc_fetch_object(self, conn, p):
        """Single-frame fetch for objects at or below one chunk."""
        oid = ObjectID(p["object_id"])
        ent = self._spill_serve_open(conn, oid)
        if ent is not None:
            loop = asyncio.get_running_loop()
            try:
                data = await loop.run_in_executor(
                    None, os.pread, ent[0].fileno(), ent[1], 0)
            except OSError:
                data = None
            self._spill_serve_close(conn, oid)
            return data
        await self._ensure_local_bytes(oid)
        try:
            buf = self.store.get_buffer(oid, timeout_ms=0)
        except Exception:
            return None
        try:
            return bytes(buf)
        finally:
            del buf
            self.store.release(oid)

    async def kill(self):
        """Chaos-test hard death (ref: test_utils.py:1419 ResourceKiller
        SIGKILLing raylets): SIGKILL every worker, drop the server with no
        lease returns / GCS goodbyes — peers must discover the loss via
        missed heartbeats and recover by retry + lineage."""
        import signal as _signal

        self._stopping = True
        await self._bg.cancel_all()
        for w in self.all_workers.values():
            try:
                os.kill(w.proc.pid, _signal.SIGKILL)
            except OSError:
                pass
        await self.server.stop()
        if self.gcs is not None:
            try:
                await self.gcs.close()
            except (rpc.RpcError, OSError):
                pass  # hard-death semantics: no goodbyes anyway
        try:
            self.store.destroy()
        except Exception:
            log.debug("store destroy failed", exc_info=True)

    async def stop(self):
        self._stopping = True
        await self._bg.cancel_all()
        for w in self.all_workers.values():
            try:
                w.proc.terminate()
            except OSError:
                pass
        # terminated workers never run their clean-exit recorder unlink:
        # drop OUR workers' recorder files (256KB each) — only ours, the
        # session rec/ dir is shared by every node of an in-process
        # cluster and other raylets' workers may still be alive
        from ray_tpu.utils import recorder as _recorder

        for w in self.all_workers.values():
            try:
                os.unlink(_recorder.worker_recorder_path(
                    self.cfg.temp_dir, self.session, w.worker_id.hex()))
            except OSError:
                pass
        try:  # removes the dir only once the LAST node emptied it
            os.rmdir(os.path.join(
                self.cfg.temp_dir, f"session_{self.session}", "rec"))
        except OSError:
            pass
        for wconn in list(self._tunnel_worker_conns.values()):
            try:
                await wconn.close()
            except Exception:
                log.debug("tunnel worker conn close failed", exc_info=True)
        self._tunnel_worker_conns.clear()
        self._tunnel_lanes.clear()
        for pconn in list(self._provider_conns.values()):
            try:
                await pconn.close()
            except Exception:
                log.debug("spill provider conn close failed", exc_info=True)
        self._provider_conns.clear()
        for conn, oid in list(self._spill_serves):
            self._spill_serve_close(conn, oid)
        await self.server.stop()
        if self.gcs is not None:
            await self.gcs.close()
        if self.cgroups.enabled:
            # leaves rmdir EBUSY until their procs exit — including workers
            # already popped from all_workers whose deferred release waiters
            # were cancelled above; retry teardown until clean or deadline
            deadline = time.monotonic() + 3.0
            while time.monotonic() < deadline:
                try:
                    if self.cgroups.teardown():
                        break
                except Exception:
                    break
                await asyncio.sleep(0.05)
        try:
            self.store.destroy()
        except Exception:
            log.debug("store destroy failed", exc_info=True)


def main():
    import argparse

    chaos.maybe_arm()  # fault schedule rides the serialized config

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs", required=True, help="host:port of the GCS")
    parser.add_argument("--num-cpus", type=float, default=float(os.cpu_count() or 1))
    parser.add_argument("--num-tpus", type=float, default=0.0)
    parser.add_argument("--resources", default="", help="k=v,k=v extra resources")
    parser.add_argument("--labels", default="", help="k=v,k=v node labels")
    parser.add_argument("--store-capacity", type=int, default=0)
    parser.add_argument("--session", default="")
    args = parser.parse_args()

    host, port = args.gcs.rsplit(":", 1)
    resources = {"CPU": args.num_cpus}
    labels: dict[str, str] = {}
    if args.num_tpus:
        resources["TPU"] = args.num_tpus
    else:
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        for k, v in TPUAcceleratorManager.get_current_node_tpu_resources().items():
            resources.setdefault(k, v)
        labels.update(TPUAcceleratorManager.get_current_node_tpu_labels())
    for kv in filter(None, args.resources.split(",")):
        k, v = kv.split("=")
        resources[k] = float(v)
    for kv in filter(None, args.labels.split(",")):
        k, v = kv.split("=")
        labels[k] = v

    raylet_box: list[Raylet] = []

    def _terminate(signum, frame):
        # SIGTERM from the head's shutdown(): unlink the shm arena and kill
        # workers, or every run leaks object_store_memory of /dev/shm
        if raylet_box:
            r = raylet_box[0]
            for w in r.all_workers.values():
                try:
                    w.proc.terminate()
                except OSError:
                    pass
            try:
                r.store.destroy()
            except Exception:  # raylint: disable=RT012 — exiting via os._exit: nowhere to report
                pass
        os._exit(0)

    import signal

    signal.signal(signal.SIGTERM, _terminate)

    async def run():
        raylet = Raylet(
            (host, int(port)),
            resources=resources,
            store_capacity=args.store_capacity or None,
            labels=labels,
            session=args.session,
        )
        raylet_box.append(raylet)
        addr = await raylet.start()
        print(f"raylet {raylet.node_id.hex()[:8]} on {addr[0]}:{addr[1]}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        if raylet_box:
            try:
                raylet_box[0].store.destroy()
            except Exception:  # raylint: disable=RT012 — ^C teardown: nowhere to report
                pass


if __name__ == "__main__":
    main()

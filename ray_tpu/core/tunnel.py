"""Cross-node fast lane: node tunnels carrying coalesced ring-format frames.

The shm fast lanes (core/fastpath.py) are same-node by design, so every
cross-node actor call, serve route and task push used to drop to per-call
RPC — one pickled spec + frame + loop write per request, exactly the
per-call overhead the local lanes spent four releases deleting. This
module is the cross-node half of the fast path, run the Pathways way
(Barham et al. 2022): a dedicated dataflow plane of persistent per-host
channels that ships descriptors, not payloads.

Topology: ONE persistent, multiplexed connection per node pair — the
driver's :class:`TunnelClient` dials the REMOTE node's raylet lazily and
keeps it (reconnect-with-backoff); the raylet terminates the tunnel and
routes records to its local workers over cached raylet->worker
connections (core/raylet.py ``rpc_tunnel_bind``/``rpc_tunnel_frame``).
Every lane multiplexed over the tunnel binds one remote worker (an actor,
a serve replica's worker, or a leased task worker).

Wire: the tunnel carries the SAME packed records the shm rings use —
``fastpath.pack_actor_task`` "A"/"C" records with per-lane seq numbers,
task "Q"/"R" records, and ``pack_reply`` completion records with stage
stamps and echoed seqs (out-of-order replies are seq-matched exactly like
ring completions). Driver-side, a :class:`TunnelRing` duck-types the
``RingPair`` face so ``FastLane`` — tx coalescing via ``txbuf`` +
adaptive defer + linger backstop, in-flight accounting, break-lane
recovery — is reused verbatim; N queued calls ship as ONE frame. A
second coalescing layer lives here: pushes from any lane landing in the
same loop tick merge into one multi-lane frame per node pair.

Payloads above ``Config.tunnel_inline_max`` do not ride the tunnel: the
sender seals them into its local shm arena and the record carries a
``fastpath.TunnelArgRef`` (node, oid, nbytes) descriptor; the receiver
adopts the whole set via ONE batched ``pull_objects`` round trip.
Results above the inline cap seal into the executing node's arena and the
completion record carries ``pack_shm_desc(size, node)`` — the record IS
the location registration.

Failure model: any tunnel fault (send failure, injected ``rpc.tunnel``
chaos, peer death) breaks every lane on that tunnel — the driver's
ordinary break-lane recovery resubmits tracked in-flight calls over the
per-call RPC path (which stays the source of truth) and surfaces
untracked serve calls as ConnectionLost to the router's retry gate. The
health loop revives lanes once the redial lands.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
import threading
import time

from ray_tpu.devtools import chaos
from ray_tpu.utils import recorder, rpc

log = logging.getLogger(__name__)

# TunnelRing status codes mirror the native ring's (fastpath._ST_*)
_ST_CLOSED = -7


def count_records(framed: bytes) -> int:
    """Number of [u32 len][payload] records in a fastpath frame buffer
    (one u32 walk — no payload copies)."""
    n = 0
    off = 0
    end = len(framed)
    while off + 4 <= end:
        (ln,) = struct.unpack_from("<I", framed, off)
        off += (4 + ln + 7) & ~7
        n += 1
    return n


class TunnelRing:
    """Per-lane ring facade over a node tunnel.

    Duck-types the subset of :class:`fastpath.RingPair` that ``FastLane``
    and the driver's submit/flush machinery touch. Pushes enqueue framed
    record bytes onto the owning tunnel's tx queue (coalesced per loop
    tick); there is no pop side — replies arrive as tunnel frames on the
    connection and feed ``CoreClient._fast_process_replies`` directly, so
    ``pop_batch`` only exists to satisfy teardown paths and returns
    nothing. ``tunnel`` marks the lane so the blocking-get steal path
    (which is a shm-ring optimization) skips it.
    """

    tunnel = True

    __slots__ = ("_t", "lane_id", "_closed", "name")

    def __init__(self, tunnel: "NodeTunnel", lane_id: int):
        self._t = tunnel
        self.lane_id = lane_id
        self._closed = False
        self.name = f"tunnel:{tunnel.addr[0]}:{tunnel.addr[1]}/{lane_id}"

    # --- push side (driver submit path; any thread) ---
    def push_batch(self, which: int, framed: bytes, timeout_ms: int = 0) -> int:
        if self._closed:
            return _ST_CLOSED
        if not self._t.enqueue(self.lane_id, bytes(framed)):
            return _ST_CLOSED
        return len(framed)

    def push_raw(self, which: int, framed: bytes, timeout_ms: int = -1) -> int:
        st = self.push_batch(which, framed, timeout_ms)
        return 0 if st >= 0 else st

    def push(self, which: int, payload: bytes, timeout_ms: int = -1) -> int:
        pad = (-(4 + len(payload))) % 8
        rec = struct.pack("<I", len(payload)) + payload + b"\x00" * pad
        return self.push_raw(which, rec, timeout_ms)

    # --- pop side (replies arrive via the connection, never here) ---
    def pop_batch(self, which: int, timeout_ms: int):
        if self._closed or self._t.down:
            return None
        if timeout_ms > 0:
            time.sleep(min(timeout_ms, 50) / 1000.0)
        return []

    def pending(self, which: int) -> int:
        return 0

    def stats(self, which: int):
        return None

    # --- lifecycle ---
    def close(self, which: int) -> None:
        self.close_pair()

    def is_closed(self, which: int) -> bool:
        return self._closed or self._t.down

    def close_pair(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._t.drop_lane(self.lane_id)

    def unlink(self) -> None:
        pass


class NodeTunnel:
    """Driver-side end of one node-pair tunnel (one per remote raylet
    address). Owns the connection, the lane registry, the tx coalescer
    and the reconnect backoff."""

    def __init__(self, client: "TunnelClient", addr: tuple):
        self.client = client
        self.core = client.core
        self.addr = tuple(addr)
        self.conn: rpc.Connection | None = None
        self.down = False  # no conn AND the last dial failed
        self.lanes: dict[int, object] = {}   # lane_id -> FastLane
        self.rings: dict[int, TunnelRing] = {}
        self._txq: list = []
        self._tx_armed = False
        self._txlock = threading.Lock()
        self._dial_lock: asyncio.Lock | None = None
        self._dial_fails = 0
        self._next_dial = 0.0  # monotonic: backoff gate for redials
        # coalescing counters (bench.py tunnel arm / tests)
        self.tx_frames = 0
        self.tx_records = 0
        self.rx_frames = 0
        self.rx_records = 0

    # ------------------------------------------------------------- connect
    async def ensure_connected(self) -> rpc.Connection | None:
        """Dial lazily with reconnect backoff (loop thread only). None
        while the backoff window of a failed dial is still open."""
        conn = self.conn
        if conn is not None and not conn._closed:
            return conn
        if self._dial_lock is None:
            self._dial_lock = asyncio.Lock()
        async with self._dial_lock:
            conn = self.conn
            if conn is not None and not conn._closed:
                return conn
            now = time.monotonic()
            if now < self._next_dial:
                return None
            try:
                conn = await rpc.connect(*self.addr, timeout=3.0)
            except Exception:
                self._dial_fails += 1
                backoff = min(self.core.cfg.tunnel_reconnect_max_s,
                              0.2 * (2 ** min(self._dial_fails, 6)))
                self._next_dial = time.monotonic() + backoff
                self.down = True
                return None
            conn.on_message = self._on_push
            self.conn = conn
            self.down = False
            self._dial_fails = 0
            return conn

    def register(self, lane_id: int, lane, ring: TunnelRing) -> None:
        self.lanes[lane_id] = lane
        self.rings[lane_id] = ring

    def drop_lane(self, lane_id: int) -> None:
        """A lane closed driver-side: forget it and tell the raylet so
        the worker's lane state is reaped (best effort)."""
        self.lanes.pop(lane_id, None)
        self.rings.pop(lane_id, None)
        conn = self.conn
        if conn is not None and not conn._closed:
            try:
                conn.send_nowait({"k": "n", "m": "tunnel_detach",
                                  "p": {"lanes": [lane_id]}})
            except Exception:
                log.debug("tunnel detach notify failed", exc_info=True)

    # ------------------------------------------------------------ tx path
    def enqueue(self, lane_id: int, framed: bytes) -> bool:
        """Queue one lane's framed records for the next tick's frame
        (any thread). False when the tunnel is unusable right now — the
        caller's lane breaks and the RPC path owns the records."""
        conn = self.conn
        if conn is None or conn._closed:
            return False
        with self._txlock:
            self._txq.append((lane_id, framed))
            arm = not self._tx_armed
            if arm:
                self._tx_armed = True
        if arm:
            loop = self.core.loop
            try:
                if threading.get_ident() == getattr(loop, "_thread_id", None):
                    loop.call_soon(self._drain_tx)
                else:
                    loop.call_soon_threadsafe(self._drain_tx)
            except RuntimeError:
                return False  # loop gone (shutdown)
        return True

    def _drain_tx(self) -> None:
        """Loop-side: ship everything queued since the last pass as ONE
        multi-lane frame — pushes from different lanes landing in the
        same tick coalesce (the proxy-side request coalescing), and a
        lane's own txbuf coalescing already merged its burst upstream.
        Stays armed while traffic flows (call_soon re-pass, the
        _drain_loop_wakes shape); disarms after one empty pass."""
        with self._txlock:
            q = self._txq
            self._txq = []
            if not q:
                self._tx_armed = False
                return
        # merge consecutive same-lane chunks, preserving per-lane order
        frames: list = []
        for lane_id, framed in q:
            if frames and frames[-1][0] == lane_id:
                frames[-1][1].append(framed)
            else:
                frames.append((lane_id, [framed]))
        frames = [(lid, parts[0] if len(parts) == 1 else b"".join(parts))
                  for lid, parts in frames]
        nrec = sum(count_records(f) for _, f in frames)
        nbytes = sum(len(f) for _, f in frames)
        if chaos.ENABLED:
            # "rpc.tunnel" fault point (tx leg). error/drop both surface
            # as a tunnel break: the frame's records are in their lanes'
            # inflight maps, so break-lane recovery resubmits them over
            # the per-call RPC path — the same road a real dead tunnel
            # takes. delay stalls the loop like a congested link.
            try:
                act = chaos.point("rpc.tunnel", dir="tx",
                                  frames=len(frames), records=nrec,
                                  bytes=nbytes)
            except chaos.ChaosError:
                self._tunnel_broke("chaos error (tx)")
                return
            if act is not None and act.kind == "drop":
                self._tunnel_broke("chaos drop (tx)")
                return
        conn = self.conn
        if conn is None or conn._closed:
            self._tunnel_broke("connection lost")
            return
        try:
            conn.send_nowait({"k": "n", "m": "tunnel_frame",
                              "p": {"frames": frames}})
        except Exception:
            self._tunnel_broke("send failed")
            return
        self.tx_frames += 1
        self.tx_records += nrec
        rec_r = recorder.get_recorder()
        if rec_r is not None:
            rec_r.record(b"", recorder.TUNNEL_TX, a0=nrec,
                         a1=nbytes & 0xFFFFFFFF, a2=nbytes >> 32)
        self.core.loop.call_soon(self._drain_tx)  # burst linger

    # ------------------------------------------------------------ rx path
    def _on_push(self, msg: dict):
        m = msg.get("m")
        if m == "tunnel_frame":
            self._on_reply_frames(msg["p"]["frames"])
        elif m == "tunnel_down":
            # the raylet lost a worker (or never knew the lane): break
            # exactly those lanes — per-call RPC fallback takes over
            for lane_id in msg["p"].get("lanes", ()):
                lane = self.lanes.pop(lane_id, None)
                ring = self.rings.pop(lane_id, None)
                if ring is not None:
                    ring._closed = True
                if lane is not None:
                    self.core._fast_break_lane(lane)

    def _on_reply_frames(self, frames) -> None:
        from ray_tpu.core import fastpath

        if chaos.ENABLED:
            try:
                act = chaos.point("rpc.tunnel", dir="rx",
                                  frames=len(frames))
            except chaos.ChaosError:
                self._tunnel_broke("chaos error (rx)")
                return
            if act is not None and act.kind == "drop":
                # dropping replies loses completions: same recovery as a
                # dead tunnel (break-lane resubmits; duplicates are
                # applied exactly once driver-side)
                self._tunnel_broke("chaos drop (rx)")
                return
        rec_r = recorder.get_recorder()
        for lane_id, recs_b in frames:
            lane = self.lanes.get(lane_id)
            if lane is None:
                continue
            recs = fastpath.unframe(recs_b)
            self.rx_frames += 1
            self.rx_records += len(recs)
            if rec_r is not None:
                rec_r.record(b"", recorder.TUNNEL_RX, a0=len(recs),
                             a1=len(recs_b) & 0xFFFFFFFF,
                             a2=len(recs_b) >> 32)
            self.core._fast_process_replies(lane, recs)

    # ------------------------------------------------------------- failure
    def _tunnel_broke(self, reason: str) -> None:
        """Break EVERY lane on this tunnel (loop thread): in-flight
        tracked calls resubmit over RPC, untracked serve calls surface
        ConnectionLost to the router. The next bind (health-loop
        revival) redials with backoff."""
        conn, self.conn = self.conn, None
        self.down = True
        self._dial_fails += 1
        self._next_dial = time.monotonic() + min(
            self.core.cfg.tunnel_reconnect_max_s,
            0.2 * (2 ** min(self._dial_fails, 6)))
        lanes = list(self.lanes.values())
        for ring in self.rings.values():
            ring._closed = True
        self.lanes.clear()
        self.rings.clear()
        with self._txlock:
            self._txq.clear()
            self._tx_armed = False
        log.debug("node tunnel to %s broke: %s (%d lanes)", self.addr,
                  reason, len(lanes))
        for lane in lanes:
            self.core._fast_break_lane(lane)
        if conn is not None:
            self.core._bg.spawn(conn.close(), self.core.loop)

    async def close(self) -> None:
        conn, self.conn = self.conn, None
        self.down = True
        for ring in self.rings.values():
            ring._closed = True
        self.lanes.clear()
        self.rings.clear()
        if conn is not None:
            await conn.close()


class TunnelClient:
    """All of one CoreClient's node tunnels, keyed by remote raylet
    address. Owned by the CoreClient; everything here runs on (or hops
    to) the core event loop."""

    def __init__(self, core):
        self.core = core
        self.tunnels: dict[tuple, NodeTunnel] = {}
        self._bind_ids = itertools.count(1)

    def tunnel_for(self, addr: tuple) -> NodeTunnel:
        addr = tuple(addr)
        t = self.tunnels.get(addr)
        if t is None:
            t = self.tunnels[addr] = NodeTunnel(self, addr)
        return t

    async def bind_lane(self, addr: tuple, kind: str,
                        worker_id: str | None = None,
                        actor_id: str | None = None):
        """Bind one lane over the node tunnel to ``addr`` (loop thread).
        Returns ``(tunnel, lane_id, ring, methods)`` or None when the
        tunnel is down / the raylet refused — the caller stays on the
        RPC path and the health loop retries later."""
        t = self.tunnel_for(addr)
        conn = await t.ensure_connected()
        if conn is None:
            return None
        payload = {"kind": kind}
        if worker_id is not None:
            payload["worker_id"] = worker_id
        if actor_id is not None:
            payload["actor_id"] = actor_id
        try:
            reply = await conn.call("tunnel_bind", payload, timeout=10)
        except Exception:
            if t.conn is conn:
                t._tunnel_broke("bind failed")
            return None
        if not isinstance(reply, dict) or not reply.get("ok"):
            return None
        lane_id = reply["lane"]
        ring = TunnelRing(t, lane_id)
        return t, lane_id, ring, reply.get("methods")

    def stats(self) -> dict:
        """Aggregate coalescing counters (bench.py tunnel arm; the
        coalesced-frame proof in tests): avg_batch == 1.0 means every
        frame carried a single record."""
        tx_f = sum(t.tx_frames for t in self.tunnels.values())
        tx_r = sum(t.tx_records for t in self.tunnels.values())
        return {
            "tunnels": len(self.tunnels),
            "lanes": sum(len(t.lanes) for t in self.tunnels.values()),
            "tx_frames": tx_f,
            "tx_records": tx_r,
            "rx_frames": sum(t.rx_frames for t in self.tunnels.values()),
            "rx_records": sum(t.rx_records for t in self.tunnels.values()),
            "avg_batch": (tx_r / tx_f) if tx_f else 0.0,
        }

    async def close(self) -> None:
        for t in list(self.tunnels.values()):
            try:
                await t.close()
            except Exception:
                log.debug("tunnel close failed", exc_info=True)
        self.tunnels.clear()

"""ctypes face of the native GCS state engine (_native/src/gcs_core.cc).

The GCS server keeps every table byte in C++ — KV maps, the write-ahead
journal, snapshot/recovery — and Python only dispatches RPCs and runs
policy (ref: src/ray/gcs/gcs_server/store_client/redis_store_client.cc +
gcs_table_storage.h role). All calls release the GIL for the native
operation.

Values are tag-encoded so arbitrary Python objects survive the byte
store: b"\\x00" + raw bytes for the common case (the wire contract is
bytes), b"\\x01" + pickle for anything else.
"""

from __future__ import annotations

import ctypes
import pickle
from typing import Any

from ray_tpu import _native

_GET_BUF = 256 * 1024  # initial copy-out buffer; grows on -9


class NativeGcsStore:
    def __init__(self, persist_path: str | None):
        self._lib = _native.get_lib()
        self._h = self._lib.rt_gcs_open(
            persist_path.encode() if persist_path else b"")
        if not self._h:
            raise OSError("could not open native gcs store")
        self._buf = ctypes.create_string_buffer(_GET_BUF)
        self._len = ctypes.c_uint64(0)

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _enc(value: Any) -> bytes:
        if isinstance(value, bytes):
            return b"\x00" + value
        if isinstance(value, bytearray):
            return b"\x00" + bytes(value)
        return b"\x01" + pickle.dumps(value)

    @staticmethod
    def _dec(blob: bytes) -> Any:
        if blob[:1] == b"\x00":
            return blob[1:]
        return pickle.loads(blob[1:])

    def _copy_call(self, fn, *args) -> bytes | None:
        """Run a copy-out API, growing the buffer on -9 (too small)."""
        while True:
            st = fn(self._h, *args,
                    ctypes.cast(self._buf, ctypes.POINTER(ctypes.c_uint8)),
                    len(self._buf), ctypes.byref(self._len))
            if st == 0:
                return self._buf.raw[: self._len.value]
            if st == -9:
                self._buf = ctypes.create_string_buffer(
                    max(self._len.value, len(self._buf) * 2))
                continue
            return None

    # ------------------------------------------------------------------ kv
    def put(self, ns: str, key: str, value: Any, *, overwrite: bool = True,
            journal: bool = True) -> bool:
        v = self._enc(value)
        k = key.encode()
        n = ns.encode()
        return bool(self._lib.rt_gcs_kv_put(
            self._h, n, len(n), k, len(k), v, len(v),
            1 if overwrite else 0, 1 if journal else 0))

    def get(self, ns: str, key: str) -> Any | None:
        k = key.encode()
        n = ns.encode()
        blob = self._copy_call(self._lib.rt_gcs_kv_get, n, len(n), k, len(k))
        return None if blob is None else self._dec(blob)

    def multi_get(self, ns: str, keys: list[str]) -> dict[str, Any]:
        return {k: self.get(ns, k) for k in keys}

    def delete(self, ns: str, key: str, *, journal: bool = True) -> bool:
        k = key.encode()
        n = ns.encode()
        return bool(self._lib.rt_gcs_kv_del(
            self._h, n, len(n), k, len(k), 1 if journal else 0))

    def exists(self, ns: str, key: str) -> bool:
        k = key.encode()
        n = ns.encode()
        return bool(self._lib.rt_gcs_kv_exists(self._h, n, len(n), k, len(k)))

    def keys(self, ns: str, prefix: str = "") -> list[str]:
        n = ns.encode()
        p = prefix.encode()
        packed = self._copy_call(
            self._lib.rt_gcs_kv_keys, n, len(n), p, len(p))
        out: list[str] = []
        if not packed:
            return out
        import struct

        off = 0
        while off + 4 <= len(packed):
            (ln,) = struct.unpack_from("<I", packed, off)
            out.append(packed[off + 4: off + 4 + ln].decode())
            off += 4 + ln
        return out

    def count(self, ns: str) -> int:
        n = ns.encode()
        return int(self._lib.rt_gcs_kv_count(self._h, n, len(n)))

    # ------------------------------------------------------- journal + snap
    def journal_aux(self, payload: bytes) -> None:
        self._lib.rt_gcs_journal_aux(self._h, payload, len(payload))

    @property
    def wal_ok(self) -> bool:
        return bool(self._lib.rt_gcs_wal_ok(self._h))

    def set_fsync(self, on: bool) -> None:
        """Opt-in machine-crash durability: snapshot writes fsync before
        the rename (+ directory fsync after), and wal_sync() becomes the
        group-commit gate for journaled table writes."""
        self._lib.rt_gcs_set_fsync(self._h, 1 if on else 0)

    def wal_sync(self) -> bool:
        """fdatasync records appended since the last sync (no-op when the
        WAL is clean). Releases the GIL for the disk sync."""
        return self._lib.rt_gcs_wal_sync(self._h) == 0

    @property
    def had_snapshot(self) -> bool:
        return bool(self._lib.rt_gcs_had_snapshot(self._h))

    @property
    def wal_records(self) -> int:
        """Records applied during open()'s WAL replay."""
        return int(self._lib.rt_gcs_wal_records(self._h))

    def snapshot(self, aux: bytes, *, skip_ns: str = "metrics") -> bool:
        return self._lib.rt_gcs_snapshot(
            self._h, aux, len(aux), skip_ns.encode()) == 0

    def recovered_snapshot_aux(self) -> bytes:
        return self._copy_call(self._lib.rt_gcs_snapshot_aux) or b""

    def recovered_aux_records(self) -> list[bytes]:
        out = []
        for i in range(int(self._lib.rt_gcs_aux_count(self._h))):
            blob = self._copy_call(self._lib.rt_gcs_aux_get, i)
            if blob is not None:
                out.append(blob)
        return out

    def close(self) -> None:
        if self._h:
            self._lib.rt_gcs_close(self._h)
            self._h = None

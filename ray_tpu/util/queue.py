"""Distributed FIFO queue backed by an async actor
(ref: python/ray/util/queue.py — Queue over an _QueueActor with
put/get/qsize/empty/full, blocking and timeout variants)."""

from __future__ import annotations

import asyncio

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote(num_cpus=0, max_concurrency=16)
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: float | None = None) -> bool:
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_nowait(self, item) -> bool:
        # async so it runs on the actor's event loop: asyncio.Queue is not
        # thread-safe and a sync method would mutate it from executor threads
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: float | None = None):
        try:
            if timeout is None:
                return True, await self._q.get()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()


class Queue:
    """Cluster-wide FIFO usable from any driver/worker/actor."""

    def __init__(self, maxsize: int = 0, *, actor_options: dict | None = None):
        self.maxsize = maxsize
        opts = actor_options or {}
        self.actor = (_QueueActor.options(**opts) if opts else _QueueActor).remote(maxsize)

    def put(self, item, block: bool = True, timeout: float | None = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full("put timed out")

    def get(self, block: bool = True, timeout: float | None = None):
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty("get timed out")
        return item

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)

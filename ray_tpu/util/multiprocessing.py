"""multiprocessing.Pool-compatible API over cluster tasks
(ref: python/ray/util/multiprocessing/pool.py — drop-in Pool so existing
multiprocessing code scales past one machine).

    from ray_tpu.util.multiprocessing import Pool
    with Pool() as p:
        print(p.map(f, range(100)))
"""

from __future__ import annotations

import os
from collections import deque
from itertools import islice
from typing import Callable, Iterable

import ray_tpu


class AsyncResult:
    """multiprocessing.pool.AsyncResult shape over ObjectRefs."""

    def __init__(self, refs: list, single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._delivered = False

    def get(self, timeout: float | None = None):
        try:
            values = ray_tpu.get(self._refs, timeout=timeout)
        except Exception as e:
            if self._error_callback and not self._delivered:
                self._delivered = True
                self._error_callback(e)
            raise
        if self._callback and not self._delivered:
            self._delivered = True
            self._callback(values[0] if self._single else values)
        return values[0] if self._single else values

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Task-backed process pool. ``processes`` bounds concurrent in-flight
    tasks (default: cluster CPU count); initializer runs lazily inside each
    executing worker process."""

    def __init__(self, processes: int | None = None, initializer=None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = int(ray_tpu.cluster_resources().get("CPU", 0)) or \
                (os.cpu_count() or 1)
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        self._closed = False
        pool_id = f"{os.getpid()}:{id(self)}"

        @ray_tpu.remote
        def _run(fn, batch, _star=False, _pool_id=pool_id, _init=initializer,
                 _initargs=initargs):
            if _init is not None:
                # once per (worker process, pool): the marker lives on a
                # module every worker has imported
                import builtins

                done = getattr(builtins, "_rt_mp_inited", None)
                if done is None:
                    done = set()
                    builtins._rt_mp_inited = done
                if _pool_id not in done:
                    _init(*_initargs)
                    done.add(_pool_id)
            if _star:
                return [fn(*a) for a in batch]
            return [fn(a) for a in batch]

        self._run = _run

    # ------------------------------------------------------------- helpers
    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _auto_chunksize(self, n: int) -> int:
        return max(1, n // (self._processes * 4) or 1)

    def _chunks(self, iterable: Iterable, chunksize: int | None):
        items = list(iterable)
        if chunksize is None:
            chunksize = self._auto_chunksize(len(items))
        return [items[i:i + chunksize] for i in
                range(0, len(items), chunksize)] or [[]]

    # ----------------------------------------------------------------- api
    def apply(self, fn: Callable, args: tuple = (), kwds: dict | None = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict | None = None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_open()
        kwds = kwds or {}

        @ray_tpu.remote
        def _call(f, a, kw):
            return f(*a, **kw)

        return AsyncResult([_call.remote(fn, args, kwds)], single=True,
                           callback=callback, error_callback=error_callback)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: int | None = None) -> list:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: int | None = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check_open()
        chunks = self._chunks(iterable, chunksize)
        refs = [self._run.remote(fn, c) for c in chunks]
        return _FlattenResult(refs, callback=callback,
                              error_callback=error_callback)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: int | None = None) -> list:
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None, callback=None,
                      error_callback=None) -> AsyncResult:
        self._check_open()
        chunks = self._chunks([tuple(a) for a in iterable], chunksize)
        refs = [self._run.remote(fn, c, True) for c in chunks]
        return _FlattenResult(refs, callback=callback,
                              error_callback=error_callback)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int | None = None):
        # validate eagerly (stdlib parity: errors surface at the call
        # site, not at first iteration), then hand off to the generator
        self._check_open()
        if chunksize is None:
            try:
                chunksize = self._auto_chunksize(len(iterable))  # type: ignore[arg-type]
            except TypeError:
                chunksize = 16  # lazy iterable: no len() to size against
        elif chunksize < 1:
            raise ValueError(f"Chunksize must be 1+, not {chunksize}")
        return self._imap_gen(fn, iter(iterable), chunksize)

    def _imap_gen(self, fn: Callable, it, chunksize: int):
        # bounded submission window: a few chunks stay in flight ahead of
        # the consumer (workers pipeline) without ever materializing the
        # iterable, so unbounded generators stream; the per-ref get is
        # the ordered yield imap's contract requires
        depth = max(2, self._processes * 2)
        window: deque = deque()

        def submit_next() -> bool:
            chunk = list(islice(it, chunksize))
            if not chunk:
                return False
            window.append(self._run.remote(fn, chunk))
            return True

        for _ in range(depth):
            if not submit_next():
                break
        # if the consumer abandons the generator mid-stream, the <= depth
        # in-flight chunks finish in the background and their results and
        # errors are discarded — same contract as stdlib Pool.imap, and
        # deliberately non-blocking (draining here would stall a `break`
        # for up to a full chunk's runtime)
        while window:
            ref = window.popleft()
            submit_next()
            for v in ray_tpu.get(ref):
                yield v

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int | None = None):
        self._check_open()
        pending = [self._run.remote(fn, c)
                   for c in self._chunks(iterable, chunksize)]
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            for v in ray_tpu.get(done[0]):
                yield v

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()


class _FlattenResult(AsyncResult):
    """AsyncResult over chunked map tasks: flattens chunk lists."""

    def __init__(self, refs, callback=None, error_callback=None):
        super().__init__(refs, single=False, callback=None,
                         error_callback=error_callback)
        self._flat_callback = callback

    def get(self, timeout: float | None = None):
        chunks = super().get(timeout)
        flat = [v for chunk in chunks for v in chunk]
        if self._flat_callback and not self._delivered:
            self._delivered = True
            self._flat_callback(flat)
        return flat

"""ActorPool: multiplex tasks over a fixed set of actors
(ref: python/ray/util/actor_pool.py — same surface: submit/get_next/
get_next_unordered/map/map_unordered/has_next/push/pop_idle)."""

from __future__ import annotations

from typing import Any, Callable, Iterable

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list[tuple[Callable, Any]] = []

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queued until an actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        idx = self._next_return_index
        if idx not in self._index_to_future:
            raise StopIteration("result already consumed")
        ref = self._index_to_future[idx]
        # get BEFORE mutating pool state: a timeout must leave the task
        # retrievable and the actor owned by the pool
        value = ray_tpu.get(ref, timeout=timeout)
        del self._index_to_future[idx]
        self._next_return_index += 1
        self._return_actor(self._future_to_actor.pop(ref))
        return value

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        refs = list(self._index_to_future.values())
        done, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not done:
            raise TimeoutError("no result within timeout")
        ref = done[0]
        value = ray_tpu.get(ref)  # ready: cannot block
        for idx, r in list(self._index_to_future.items()):
            if r is ref:
                del self._index_to_future[idx]
                break
        self._return_actor(self._future_to_actor.pop(ref))
        return value

    def map(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def push(self, actor) -> None:
        """Add an idle actor to the pool."""
        self._return_actor(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None."""
        return self._idle.pop() if self._idle else None

"""Task/actor scheduling strategies (ref:
python/ray/util/scheduling_strategies.py:15,:41,:135).

Pass via ``.options(scheduling_strategy=...)``:

* ``"DEFAULT"`` — hybrid top-k (prefer the local node until it is
  loaded, then randomized best-fit; core/policy.py).
* ``"SPREAD"`` — round-robin leases across feasible nodes (ref:
  spread_scheduling_policy.cc).
* :class:`NodeAffinitySchedulingStrategy` — pin to one node; ``soft``
  falls back to DEFAULT if that node is gone/full (ref:
  scheduling_strategies.py:41).
* :class:`NodeLabelSchedulingStrategy` — place only on nodes whose
  labels match ``hard`` (value or any-of list); among those, prefer
  nodes matching ``soft`` (ref: scheduling_strategies.py:135,
  node_label_scheduling_policy.h:25). Hard-infeasible submissions fail
  fast with a scheduling error rather than parking forever.

The placement-group strategy keeps its dedicated ``placement_group=``
option; :class:`PlacementGroupSchedulingStrategy` is accepted for
API parity.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class PlacementGroupSchedulingStrategy:
    """API-parity wrapper (ref: scheduling_strategies.py:15)."""

    placement_group: object
    placement_group_bundle_index: int = -1


@dataclass
class NodeAffinitySchedulingStrategy:
    """Run on the given node. ``soft=False`` fails if the node is dead
    or full; ``soft=True`` falls back to the default policy."""

    node_id: str  # hex node id (ray_tpu.nodes()[i]["node_id"].hex())
    soft: bool = False

    def to_wire(self) -> dict:
        return {"type": "node_affinity", "node_id": self.node_id,
                "soft": bool(self.soft)}


@dataclass
class NodeLabelSchedulingStrategy:
    """Label-constrained placement. ``hard``/``soft`` map label keys to a
    required value or a list of acceptable values (the reference's In()
    operator); a ``hard`` miss on every node fails the submission."""

    hard: dict = field(default_factory=dict)
    soft: dict = field(default_factory=dict)

    def to_wire(self) -> dict:
        norm = lambda d: {k: list(v) if isinstance(v, (list, tuple, set))
                          else [v] for k, v in d.items()}
        return {"type": "node_label", "hard": norm(self.hard),
                "soft": norm(self.soft)}


def normalize(strategy) -> dict | None:
    """Normalize the user-facing option into the wire dict (None =
    default hybrid policy)."""
    if strategy is None or strategy == "DEFAULT":
        return None
    if strategy == "SPREAD":
        return {"type": "spread"}
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return None  # carried by the dedicated placement_group option
    if isinstance(strategy, (NodeAffinitySchedulingStrategy,
                             NodeLabelSchedulingStrategy)):
        return strategy.to_wire()
    raise ValueError(f"unknown scheduling_strategy {strategy!r}")


def labels_match(labels: dict, selector: dict) -> bool:
    """selector maps label keys to an acceptable value or list of values
    (all keys must match). Handles both the wire form (lists) and bare
    values, so call sites never need to re-normalize — a stray
    ``list("tpu")`` would silently match nothing."""
    for k, v in selector.items():
        accepted = v if isinstance(v, (list, tuple, set)) else (v,)
        if labels.get(k) not in accepted:
            return False
    return True

"""User-facing metrics API (ref: ray.util.metrics Counter/Gauge/Histogram,
util/metrics.py:163/:216/:294).

Metrics defined in driver, task, or actor code register in the process-
local registry and ride the same export pipeline as the runtime's own
metrics (worker flush -> GCS -> ray_tpu.state.cluster_metrics /
dashboard), tagged per the declared tag_keys::

    from ray_tpu.util.metrics import Counter
    requests = Counter("app_requests", description="...", tag_keys=("route",))
    requests.inc(tags={"route": "/infer"})
"""

from ray_tpu.utils.metrics import Counter, Gauge, Histogram

__all__ = ["Counter", "Gauge", "Histogram"]

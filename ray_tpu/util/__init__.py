"""User-facing distributed utilities (ref: python/ray/util/*)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = ["ActorPool", "Queue", "Empty", "Full"]

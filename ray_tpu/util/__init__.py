"""User-facing distributed utilities (ref: python/ray/util/*)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool", "Queue", "Empty", "Full",
    "NodeAffinitySchedulingStrategy", "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]

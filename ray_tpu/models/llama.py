"""Flagship model: Llama-family decoder-only transformer, TPU-native.

Functional pytree implementation (no framework Module state): params are a
dict keyed so `parallel.sharding.PartitionRules.llama()` maps every weight
to its TP/FSDP axes by path regex, attention dispatches to
plain/flash/ring/ulysses by mesh (ops/attention.py), each block is wrapped
in jax.checkpoint (remat) to trade FLOPs for HBM, and optional MoE layers
use the expert-parallel dispatch from parallel/moe.py. Matches the model
families the reference serves through vLLM (Llama-2/3 in BASELINE.json
north-star configs) but as a native JAX program.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name

from ray_tpu.ops.attention import attention
from ray_tpu.ops.basic import rms_norm, rope, rope_freqs, swiglu


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"
    # MoE: 0 experts = dense; else every `moe_every`-th layer is MoE
    n_experts: int = 0
    moe_every: int = 2
    capacity_factor: float = 1.25
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        return cls(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                   n_kv_heads=2, d_ff=128, max_seq_len=128, dtype="float32", **kw)

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls(vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
                   n_kv_heads=32, d_ff=11008, max_seq_len=4096)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, d_ff=14336, max_seq_len=8192,
                   rope_theta=500000.0)


def _dense(key, d_in, d_out, dtype):
    scale = (2.0 / (d_in + d_out)) ** 0.5
    return {"kernel": (jax.random.normal(key, (d_in, d_out)) * scale).astype(dtype)}


def _is_moe_layer(cfg: LlamaConfig, i: int) -> bool:
    return cfg.n_experts > 0 and (i % cfg.moe_every == cfg.moe_every - 1)


def llama_init(key, cfg: LlamaConfig) -> dict:
    dtype = jnp.dtype(cfg.dtype)
    hd = cfg.head_dim
    keys = jax.random.split(key, cfg.n_layers * 8 + 3)
    ki = iter(range(len(keys)))
    params: dict = {
        "tok": {
            "embedding": (
                jax.random.normal(keys[next(ki)], (cfg.vocab_size, cfg.d_model)) * 0.02
            ).astype(dtype)
        }
    }
    for i in range(cfg.n_layers):
        layer = {
            "attn_norm": {"scale": jnp.ones((cfg.d_model,), dtype)},
            "wq": _dense(keys[next(ki)], cfg.d_model, cfg.n_heads * hd, dtype),
            "wk": _dense(keys[next(ki)], cfg.d_model, cfg.n_kv_heads * hd, dtype),
            "wv": _dense(keys[next(ki)], cfg.d_model, cfg.n_kv_heads * hd, dtype),
            "wo": _dense(keys[next(ki)], cfg.n_heads * hd, cfg.d_model, dtype),
            "ffn_norm": {"scale": jnp.ones((cfg.d_model,), dtype)},
        }
        if _is_moe_layer(cfg, i):
            e = cfg.n_experts
            k1, k2, k3 = jax.random.split(keys[next(ki)], 3)
            layer["moe"] = {
                "gate": {"kernel": (jax.random.normal(k1, (cfg.d_model, e)) * 0.02).astype(dtype)},
                "w_up": {"kernel": (jax.random.normal(k2, (e, cfg.d_model, cfg.d_ff)) * 0.02).astype(dtype)},
                "w_down": {"kernel": (jax.random.normal(k3, (e, cfg.d_ff, cfg.d_model)) * 0.02).astype(dtype)},
            }
        else:
            layer["w_gate"] = _dense(keys[next(ki)], cfg.d_model, cfg.d_ff, dtype)
            layer["w_up"] = _dense(keys[next(ki)], cfg.d_model, cfg.d_ff, dtype)
            layer["w_down"] = _dense(keys[next(ki)], cfg.d_ff, cfg.d_model, dtype)
        params[f"layers_{i}"] = layer
    params["norm"] = {"scale": jnp.ones((cfg.d_model,), dtype)}
    params["lm_head"] = _dense(keys[next(ki)], cfg.d_model, cfg.vocab_size, dtype)
    return params


def _block(layer, x, cos, sin, cfg: LlamaConfig, mesh, attn_impl, seq_axis):
    B, T, D = x.shape
    hd = cfg.head_dim
    h = rms_norm(x, layer["attn_norm"]["scale"])
    q = (h @ layer["wq"]["kernel"]).reshape(B, T, cfg.n_heads, hd)
    k = (h @ layer["wk"]["kernel"]).reshape(B, T, cfg.n_kv_heads, hd)
    v = (h @ layer["wv"]["kernel"]).reshape(B, T, cfg.n_kv_heads, hd)
    q = rope(q, cos, sin)
    k = rope(k, cos, sin)
    # named for the remat policy: the flash backward consumes q/k/v
    # directly, so saving them skips recomputing three projections + rope
    # per layer in the backward pass (bytes: 3*d_model*T per layer)
    q = _checkpoint_name(q, "attn_qkv")
    k = _checkpoint_name(k, "attn_qkv")
    v = _checkpoint_name(v, "attn_qkv")
    att = attention(q, k, v, causal=True, mesh=mesh, seq_axis=seq_axis, impl=attn_impl)
    # named so the remat policy can SAVE attention outputs: recomputing
    # the O(T^2) attention forward in the backward pass costs ~10 MFU
    # points at 8k context, while saving att is only d_model*T per layer
    att = _checkpoint_name(att, "attn_out")
    x = x + att.reshape(B, T, cfg.n_heads * hd) @ layer["wo"]["kernel"]

    h = rms_norm(x, layer["ffn_norm"]["scale"])
    if "moe" in layer:
        from ray_tpu.parallel.moe import moe_ffn

        out, aux = moe_ffn(
            h,
            layer["moe"]["gate"]["kernel"],
            layer["moe"]["w_up"]["kernel"],
            layer["moe"]["w_down"]["kernel"],
            capacity_factor=cfg.capacity_factor,
            mesh=mesh,
        )
        x = x + out
    else:
        aux = 0.0
        x = x + swiglu(h, layer["w_gate"]["kernel"], layer["w_up"]["kernel"],
                       layer["w_down"]["kernel"])
    return x, aux


def _maybe_remat_block(cfg: LlamaConfig):
    """One remat policy for all forward paths (dense, pipelined).

    Selective remat: attention outputs (+lse), post-rope q/k/v and the
    FFN gate/up products are SAVED (~(4*d_model + 2*d_ff) * T * L bytes
    of residuals, ~10x d_model*T*L with the usual d_ff ratio); norms and
    the remaining matmuls rematerialize. Saving attention kills the
    O(T^2) flash-forward recompute (43% -> 49% MFU at 8k measured);
    saving qkv/ffn trades affordable HBM for the rest (-> 54% at 8k,
    69% at 512). Set remat=False only when everything fits."""
    if not cfg.remat:
        return _block
    return jax.checkpoint(
        _block, static_argnums=(4, 5, 6, 7),
        policy=jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_qkv", "ffn_hidden"),
    )


def _ce_loss(logits, targets):
    """Next-token cross entropy shared by llama_loss / llama_pp_loss."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0].mean()


def llama_forward(params, tokens, cfg: LlamaConfig, *, mesh=None,
                  attn_impl: str = "auto", seq_axis: str | None = "sp"):
    """tokens: [B, T] int32 -> logits [B, T, V]."""
    if mesh is not None and (seq_axis not in mesh.shape or mesh.shape[seq_axis] == 1):
        seq_axis = None
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    x = params["tok"]["embedding"][tokens]
    aux_total = 0.0
    block = _maybe_remat_block(cfg)
    for i in range(cfg.n_layers):
        x, aux = block(params[f"layers_{i}"], x, cos, sin, cfg, mesh, attn_impl, seq_axis)
        aux_total = aux_total + aux
    x = rms_norm(x, params["norm"]["scale"])
    logits = x @ params["lm_head"]["kernel"]
    return logits, aux_total


def llama_loss(params, batch, cfg: LlamaConfig, *, mesh=None, attn_impl="auto"):
    """Next-token cross entropy; batch: {"tokens": [B, T+1]}."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = llama_forward(params, inputs, cfg, mesh=mesh, attn_impl=attn_impl)
    return _ce_loss(logits, targets) + 0.01 * aux


# ------------------------------------------------------- pipelined variant
def llama_pp_init(key, cfg: LlamaConfig, n_stages: int) -> dict:
    """Init with transformer layers stacked for pipeline parallelism:
    ``stages`` leaves carry a leading [n_stages, layers_per_stage] axis
    (sharded on the ``pp`` mesh axis by pipeline_apply); embedding/norm/head
    stay in ``dense`` and run outside the pipeline body. Dense layers only
    (MoE composes with ep/fsdp meshes on the non-pipelined path)."""
    if cfg.n_experts:
        raise ValueError("pipelined llama requires dense layers (n_experts=0)")
    if cfg.n_layers % n_stages:
        raise ValueError(f"{cfg.n_layers} layers not divisible by {n_stages} stages")
    params = llama_init(key, cfg)
    per = cfg.n_layers // n_stages
    layers = [params.pop(f"layers_{i}") for i in range(cfg.n_layers)]
    stages = []
    for s in range(n_stages):
        chunk = layers[s * per: (s + 1) * per]
        stages.append(jax.tree.map(lambda *xs: jnp.stack(xs), *chunk))  # [per,...]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stages)  # [pp, per, ...]
    return {"dense": params, "stages": stacked}


def _block_tp(layer, x, cos, sin, cfg: LlamaConfig, tp_axis: str):
    """Megatron-style tensor-parallel transformer block for use INSIDE a
    shard_map body (each tp rank holds a weight slice): q/k/v and
    gate/up are column-parallel (heads / ff split across ranks), wo and
    w_down row-parallel with a psum to rejoin the residual stream."""
    from jax import lax

    B, T, D = x.shape
    hd = cfg.head_dim
    tp = (lax.axis_size(tp_axis) if hasattr(lax, "axis_size")
          else lax.psum(1, tp_axis))  # jax 0.4.x spelling
    h = rms_norm(x, layer["attn_norm"]["scale"])
    q = (h @ layer["wq"]["kernel"]).reshape(B, T, cfg.n_heads // tp, hd)
    k = (h @ layer["wk"]["kernel"]).reshape(B, T, cfg.n_kv_heads // tp, hd)
    v = (h @ layer["wv"]["kernel"]).reshape(B, T, cfg.n_kv_heads // tp, hd)
    q = rope(q, cos, sin)
    k = rope(k, cos, sin)
    att = attention(q, k, v, causal=True, mesh=None, seq_axis=None,
                    impl="plain")
    att = lax.psum(att.reshape(B, T, -1) @ layer["wo"]["kernel"], tp_axis)
    x = x + att
    h = rms_norm(x, layer["ffn_norm"]["scale"])
    ffn = (jax.nn.silu(h @ layer["w_gate"]["kernel"])
           * (h @ layer["w_up"]["kernel"])) @ layer["w_down"]["kernel"]
    return x + lax.psum(ffn, tp_axis)


def pp_stage_param_specs(stacked_params, *, pp_axis: str = "pp",
                         tp_axis: str | None = None):
    """PartitionSpecs for pipeline stage weights: leading stage axis on
    pp; with ``tp_axis``, attention/ffn weights additionally split
    Megatron-style (column for wq/wk/wv/w_gate/w_up, row for
    wo/w_down)."""
    from jax.sharding import PartitionSpec as P

    col = {"wq", "wk", "wv", "w_gate", "w_up"}
    row = {"wo", "w_down"}

    def spec(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        if tp_axis:
            if any(n in col for n in names):
                return P(pp_axis, *([None] * (leaf.ndim - 2)), tp_axis)
            if any(n in row for n in names):
                return P(pp_axis, *([None] * (leaf.ndim - 3)), tp_axis, None)
        return P(pp_axis)

    return jax.tree_util.tree_map_with_path(spec, stacked_params)


def llama_pp_loss(params, batch, cfg: LlamaConfig, mesh, *, n_microbatches: int,
                  attn_impl: str = "plain", batch_axis: str | None = "dp",
                  tp_axis: str | None = None):
    """Next-token CE through a GPipe pipeline over the mesh's pp axis
    (ref: SURVEY §2.3 PP — the reference only gets PP via vLLM config or
    compiled-graph p2p channels; here the pipeline is one jitted SPMD
    program, see parallel/pipeline.py). With ``tp_axis`` each stage ALSO
    runs Megatron tensor parallelism over that mesh axis — the full
    dp x tp x pp composition in one program."""
    from jax import lax

    from ray_tpu.parallel.pipeline import pipeline_apply

    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    dense = params["dense"]
    x = dense["tok"]["embedding"][inputs]
    cos, sin = rope_freqs(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    block = _maybe_remat_block(cfg)

    if tp_axis is not None:
        tp_block = (jax.checkpoint(_block_tp, static_argnums=(4, 5))
                    if cfg.remat else _block_tp)

        def stage_fn(stage_params, h):
            def layer_step(h, layer):
                return tp_block(layer, h, cos, sin, cfg, tp_axis), None

            h, _ = lax.scan(layer_step, h, stage_params)
            return h

        param_specs = pp_stage_param_specs(
            params["stages"], tp_axis=tp_axis)
    else:
        def stage_fn(stage_params, h):
            def layer_step(h, layer):
                h, _ = block(layer, h, cos, sin, cfg, None, attn_impl, None)
                return h, None

            h, _ = lax.scan(layer_step, h, stage_params)
            return h

        param_specs = None

    x = pipeline_apply(stage_fn, params["stages"], x, mesh,
                       n_microbatches=n_microbatches, batch_axis=batch_axis,
                       param_specs=param_specs)
    x = rms_norm(x, dense["norm"]["scale"])
    return _ce_loss(x @ dense["lm_head"]["kernel"], targets)


def make_train_step(cfg: LlamaConfig, optimizer, *, mesh=None, attn_impl="auto",
                    donate: bool = True):
    """Returns jitted (params, opt_state, batch) -> (params, opt_state, loss).

    Shard via jit's in_shardings at the call site (train/ wires this to
    PartitionRules.llama over the worker-group mesh).
    """

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: llama_loss(p, batch, cfg, mesh=mesh, attn_impl=attn_impl)
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())

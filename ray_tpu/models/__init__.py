"""Model zoo: flagship Llama-family transformer, ResNet, MLP."""

from ray_tpu.models.llama import LlamaConfig, llama_forward, llama_init  # noqa: F401

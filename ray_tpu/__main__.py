"""`python -m ray_tpu <command>` — the CLI entry point."""
from ray_tpu.scripts import main

main()

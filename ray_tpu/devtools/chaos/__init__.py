"""Deterministic, cluster-wide fault injection (the chaos subsystem).

The role of the reference's reusable fault-injection harness (ref:
_private/test_utils.py:1419 ResourceKiller + the chaos release tests),
generalized the way Basiri et al. (IEEE Software '16) frame chaos
engineering: every robustness property the runtime ships — task retries,
lease spillback, ring RPC-spill, WAL recovery, OOM kills — is exercised
by SEEDED, REPLAYABLE fault schedules instead of hand-rolled test
threads.

Three layers:

- **Fault points** (`chaos.point("ring.push", ...)`): named hooks
  threaded through the L1-L4 hot paths (fastpath rings, store seal, RPC
  send, GCS WAL append, raylet lease grant, worker exec). Call sites
  guard with ``if chaos.ENABLED:`` — when chaos is off (the default and
  the production state) a fault point is ONE module-attribute load and a
  falsy branch, no function call, no config lookup (bench.py
  ``chaos_overhead_us``).
- **Native fault arms** (ring.cc / store.cc): env-gated counters below
  Python that force partial ring pushes, ring wait timeouts, and store
  seal failures — see :func:`arm_native`.
- **Process-level killers** (:mod:`.killers`): seeded interval/burst
  raylet- and worker-killers with capacity restore.

A :class:`ChaosController` (:mod:`.controller`) runs a
:class:`ChaosPlan` (:mod:`.plan`): ``seed`` + ordered ``(point, match,
action, timing)`` rules with actions **delay / drop / duplicate / error
/ corrupt / kill**. The same seed over the same call sequence yields a
byte-identical fault log (``controller.signature()``). Every fired
fault is appended to a per-process JSONL under the session chaos dir
(``state.list_chaos_events()``) and stamped into the flight recorder
(utils/recorder.py stage ``chaos``) so a failed run leaves a replayable
trace.

CLI: ``python -m ray_tpu chaos run plan.json -- <cmd...>`` (see
:mod:`.cli`); config: ``RT_CHAOS_ENABLED`` / ``RT_CHAOS_PLAN`` /
``RT_CHAOS_SEED`` / ``RT_CHAOS_LOG_DIR``, serialized to every spawned
process like the rest of the flag table.
"""

from __future__ import annotations

import os

from ray_tpu.devtools.chaos.controller import (  # noqa: F401  (public API)
    Act,
    ChaosController,
    ChaosError,
)
from ray_tpu.devtools.chaos.plan import ChaosPlan, ChaosRule  # noqa: F401

#: THE hot-path gate. Call sites do ``if chaos.ENABLED: chaos.point(...)``
#: — a module-attribute load and a truth test when disabled, nothing else.
ENABLED = False

_controller: ChaosController | None = None


def point(name: str, payload: bytes | None = None, /, **ctx):
    """Fire the fault point ``name``. Only called behind an ``ENABLED``
    guard. Returns None (proceed) or an :class:`Act` the call site must
    honor (``drop`` / ``duplicate`` / ``corrupt`` with the mangled
    payload); ``delay`` sleeps here, ``error`` raises
    :class:`ChaosError`, ``kill`` SIGKILLs this process."""
    ctrl = _controller
    if ctrl is None:
        return None
    return ctrl.fire(name, payload, ctx)


def get_controller() -> ChaosController | None:
    return _controller


def enable(plan: ChaosPlan, log_dir: str | None = None) -> ChaosController:
    """Arm chaos in this process: compile ``plan``, open the per-process
    event log, apply the plan's native arms, flip :data:`ENABLED`."""
    global ENABLED, _controller
    if any(r.cluster_once for r in plan.rules):
        # per-run id for cluster_once sentinels: the first armer (the
        # driver, ahead of any spawn) stamps it into the environment so
        # every descendant process shares one claim namespace, and a
        # REUSED log dir re-arms the rule on the next run
        import time as _time

        os.environ.setdefault(
            "RT_CHAOS_RUN_ID",
            f"{os.getpid():x}-{int(_time.time() * 1e3):x}")
    log_path = None
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"chaos-{os.getpid()}.jsonl")
    _controller = ChaosController(plan, log_path=log_path)
    if plan.native:
        arm_native(**plan.native)
    ENABLED = True
    return _controller


def disable() -> None:
    """Disarm: fault points compile back to the falsy-gate no-op and the
    native arms reset to 0."""
    global ENABLED, _controller
    ENABLED = False
    ctrl, _controller = _controller, None
    if ctrl is not None:
        ctrl.close()
        if ctrl.plan.native:
            arm_native()  # reset every armed counter


def maybe_arm() -> bool:
    """Arm from the flag table (RT_CHAOS_ENABLED / RT_CHAOS_PLAN /
    RT_CHAOS_SEED / RT_CHAOS_LOG_DIR). Called at every process
    entrypoint (driver init, worker/raylet/GCS main); a no-op returning
    False when chaos is off — the common case costs one config read at
    process start, never on any hot path."""
    from ray_tpu.config import get_config

    if ENABLED:
        return True
    cfg = get_config()
    if not getattr(cfg, "chaos_enabled", False):
        return False
    plan = (ChaosPlan.load(cfg.chaos_plan) if cfg.chaos_plan
            else ChaosPlan(seed=0, rules=[]))
    if cfg.chaos_seed >= 0:
        plan.seed = cfg.chaos_seed
    enable(plan, log_dir=default_log_dir(cfg))
    return True


def default_log_dir(cfg=None) -> str:
    from ray_tpu.config import get_config

    cfg = cfg or get_config()
    return cfg.chaos_log_dir or os.path.join(cfg.temp_dir, "chaos")


def note(name: str, action: str, **ctx) -> None:
    """Record an externally-executed fault (e.g. a killer's SIGKILL) in
    the chaos event log without running any rule. No-op when disarmed."""
    ctrl = _controller
    if ctrl is not None:
        ctrl.log_external(name, action, ctx)


def arm_native(ring_partial_every: int = 0, ring_timeout_every: int = 0,
               store_seal_fail_every: int = 0) -> None:
    """Set the native fault-arm counters in ring.cc / store.cc (0
    disarms). The same arms read ``RT_CHAOS_RING_PARTIAL_EVERY`` /
    ``RT_CHAOS_RING_TIMEOUT_EVERY`` / ``RT_CHAOS_STORE_SEAL_FAIL_EVERY``
    from the environment at library load, which is how spawned workers
    inherit them; this setter re-arms a library that is already
    loaded."""
    from ray_tpu import _native

    lib = _native.get_lib()
    lib.rt_ring_chaos_set(int(ring_partial_every), int(ring_timeout_every))
    lib.rt_store_chaos_set(int(store_seal_fail_every))

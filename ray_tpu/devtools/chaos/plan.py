"""ChaosPlan: the declarative, seeded fault schedule.

A plan is JSON so it can be checked into a repo, attached to a bug
report, and replayed byte-for-byte (``python -m ray_tpu chaos run
plan.json -- <cmd>``):

    {
      "seed": 7,
      "rules": [
        {"point": "worker.exec", "action": "kill", "every": 40,
         "max_fires": 3},
        {"point": "rpc.send", "match": {"method": "kv_put"},
         "action": "delay", "delay_ms": 25, "prob": 0.1},
        {"point": "ring.push", "action": "drop", "after": 100,
         "every": 50}
      ],
      "native": {"ring_partial_every": 3}
    }

Rule fields:

- ``point``: fault-point name, exact or an ``fnmatch`` glob
  (``"gcs.*"``). See README § Fault injection for the point table.
- ``match``: optional ``{ctx_key: value}`` equality filter against the
  keyword context the call site passes to ``chaos.point`` — e.g. fire
  only on a named task or a specific RPC method.
- ``action``: ``delay`` (sleep ``delay_ms``) / ``drop`` / ``duplicate``
  / ``error`` (raise ChaosError) / ``corrupt`` (flip one seeded byte of
  the site's payload) / ``kill`` (SIGKILL this process).
- timing: ``after`` (skip the first N eligible calls), ``every`` (then
  fire on every Nth), ``prob`` (seeded coin flip per eligible call),
  ``max_fires`` (stop after N fires). All optional; a rule with none of
  them fires on every eligible call.
- ``cluster_once``: fire at most once ACROSS the whole cluster run, not
  per process. Controllers are per-process, so without this a "lose one
  shard" kill rule would strike every fresh worker the recovery path
  retries onto, defeating the recovery it means to test. Implemented as
  an O_EXCL sentinel file in the shared chaos log dir, namespaced by
  the per-run RT_CHAOS_RUN_ID (stamped at arm time, inherited by every
  child) so a reused log dir re-arms the rule each run; falls back to
  per-process once when no log dir is configured.

Determinism: rules are evaluated in plan order, each owns a
``random.Random`` seeded from ``(plan.seed, rule index)``, and every
counter advances only on rule-eligible calls — the same seed over the
same call sequence makes the same decisions.
"""

from __future__ import annotations

import dataclasses
import json

ACTIONS = ("delay", "drop", "duplicate", "error", "corrupt", "kill")
_NATIVE_ARMS = ("ring_partial_every", "ring_timeout_every",
                "store_seal_fail_every")


@dataclasses.dataclass
class ChaosRule:
    point: str
    action: str
    match: dict = dataclasses.field(default_factory=dict)
    delay_ms: float = 10.0
    prob: float | None = None
    every: int = 0
    after: int = 0
    max_fires: int = 0  # 0 = unlimited
    cluster_once: bool = False  # at most one fire across ALL processes

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown chaos action {self.action!r} (choose from "
                f"{', '.join(ACTIONS)})")
        if self.prob is not None and not (0.0 <= self.prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")
        if self.every < 0 or self.after < 0 or self.max_fires < 0:
            raise ValueError("every/after/max_fires must be >= 0")

    def as_dict(self) -> dict:
        d = {"point": self.point, "action": self.action}
        if self.match:
            d["match"] = dict(self.match)
        if self.action == "delay":
            d["delay_ms"] = self.delay_ms
        for k in ("prob", "every", "after", "max_fires", "cluster_once"):
            v = getattr(self, k)
            if v:
                d[k] = v
        return d


@dataclasses.dataclass
class ChaosPlan:
    seed: int = 0
    rules: list[ChaosRule] = dataclasses.field(default_factory=list)
    #: native fault arms applied at enable() (see chaos.arm_native)
    native: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self.rules = [r if isinstance(r, ChaosRule) else ChaosRule(**r)
                      for r in self.rules]
        unknown = set(self.native) - set(_NATIVE_ARMS)
        if unknown:
            raise ValueError(
                f"unknown native arms {sorted(unknown)} (choose from "
                f"{', '.join(_NATIVE_ARMS)})")

    @classmethod
    def from_json(cls, text: str) -> "ChaosPlan":
        raw = json.loads(text)
        return cls(seed=int(raw.get("seed", 0)), rules=raw.get("rules", []),
                   native=raw.get("native", {}))

    @classmethod
    def load(cls, path: str) -> "ChaosPlan":
        """Load a plan file; ``path`` may also be an inline JSON object
        (starts with '{') so RT_CHAOS_PLAN works without a file."""
        if path.lstrip().startswith("{"):
            return cls.from_json(path)
        with open(path) as f:
            return cls.from_json(f.read())

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "rules": [r.as_dict() for r in self.rules],
            **({"native": dict(self.native)} if self.native else {}),
        }, indent=2)

"""`python -m ray_tpu chaos` — run workloads under a fault schedule.

    ray_tpu chaos run plan.json -- python workload.py
    ray_tpu chaos run --seed 9 plan.json -- python -m pytest tests/x.py
    ray_tpu chaos validate plan.json
    ray_tpu chaos events [--log-dir DIR]

(`run` flags go BEFORE the plan path: everything after it is the
workload's own argv.)

``run`` exports the RT_CHAOS_* flags (picked up by every process the
workload spawns — driver, raylets, workers, GCS — via the serialized
config), executes the command, then prints a summary of every fault that
fired across all of them from the shared JSONL event log. The child's
exit code is passed through, so a chaos run drops into CI unchanged.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from ray_tpu.devtools.chaos.plan import ChaosPlan


def read_events(log_dir: str) -> list[dict]:
    """Merge every process's chaos JSONL under ``log_dir``, oldest
    first. Unreadable/torn lines are skipped (a SIGKILL mid-write must
    not sink the report)."""
    events: list[dict] = []
    try:
        names = sorted(os.listdir(log_dir))
    except OSError:
        return events
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        try:
            with open(os.path.join(log_dir, name)) as f:
                for line in f:
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    events.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0),
                               e.get("n", 0)))
    return events


def add_chaos_parser(sub):
    p = sub.add_parser("chaos",
                       help="deterministic fault injection (devtools/chaos)")
    csub = p.add_subparsers(dest="chaos_cmd", required=True)

    runp = csub.add_parser("run", help="run a command under a chaos plan")
    runp.add_argument("--seed", type=int, default=None,
                      help="override the plan's seed")
    runp.add_argument("--log-dir", default=None,
                      help="fault-event log dir (default: fresh dir under "
                           "the session temp tree)")
    # flags must precede the plan: everything after it (REMAINDER) is the
    # workload's own argv, passed through untouched
    runp.add_argument("plan", help="path to a ChaosPlan JSON file")
    runp.add_argument("command", nargs="...",
                      help="workload, e.g. -- python script.py")

    vp = csub.add_parser("validate", help="parse + echo a compiled plan")
    vp.add_argument("plan")

    ep = csub.add_parser("events", help="print the merged fault-event log")
    ep.add_argument("--log-dir", default=None)
    return p


def cmd_chaos(args) -> int:
    from ray_tpu.devtools import chaos

    if args.chaos_cmd == "validate":
        plan = ChaosPlan.load(args.plan)
        print(plan.to_json())
        print(f"ok: {len(plan.rules)} rule(s), seed={plan.seed}",
              file=sys.stderr)
        return 0

    if args.chaos_cmd == "events":
        log_dir = args.log_dir or chaos.default_log_dir()
        print(json.dumps(read_events(log_dir), indent=2))
        return 0

    # ------------------------------------------------------------------ run
    plan = ChaosPlan.load(args.plan)  # fail fast on a broken plan
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("chaos run needs a command, e.g. -- python workload.py",
              file=sys.stderr)
        return 2
    log_dir = args.log_dir
    if log_dir is None:
        import tempfile

        log_dir = tempfile.mkdtemp(prefix="rt_chaos_")
    os.makedirs(log_dir, exist_ok=True)
    env = dict(os.environ)
    env["RT_CHAOS_ENABLED"] = "1"
    # inline-JSON plans pass through verbatim; only real paths absolutize
    # (children may run from a different cwd)
    env["RT_CHAOS_PLAN"] = (args.plan if args.plan.lstrip().startswith("{")
                            else os.path.abspath(args.plan))
    env["RT_CHAOS_LOG_DIR"] = log_dir
    # fresh per-run id: cluster_once sentinels are namespaced by it, so
    # re-running against the SAME log dir re-arms those rules
    env["RT_CHAOS_RUN_ID"] = f"{os.getpid():x}-{int(time.time() * 1e3):x}"
    if args.seed is not None:
        env["RT_CHAOS_SEED"] = str(args.seed)
    # native arms also ride plain env so C++ picks them up at dlopen in
    # every child, not only where maybe_arm() runs
    for arm, value in (plan.native or {}).items():
        env["RT_CHAOS_" + arm.upper()] = str(value)
    proc = subprocess.run(command, env=env)

    events = read_events(log_dir)
    by_kind: dict[tuple, int] = {}
    for ev in events:
        key = (ev.get("point", "?"), ev.get("action", "?"))
        by_kind[key] = by_kind.get(key, 0) + 1
    print(f"\nchaos: {len(events)} fault(s) fired "
          f"(seed={args.seed if args.seed is not None else plan.seed}, "
          f"log: {log_dir})", file=sys.stderr)
    for (pt, action), n in sorted(by_kind.items()):
        print(f"  {pt:<24} {action:<10} ×{n}", file=sys.stderr)
    return proc.returncode

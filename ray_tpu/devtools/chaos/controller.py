"""ChaosController: deterministic rule evaluation + the fault-event log.

``fire()`` is the single funnel every armed fault point goes through:
rule matching, seeded timing decisions, and event logging happen under
one lock (points fire from ring-pump threads, executor threads, and
event loops concurrently); the SIDE EFFECTS — sleeping, raising,
SIGKILL — happen after the lock is released so one delayed point never
serializes the rest of the process.

Every fired fault is (1) appended to ``self.events``, (2) appended as a
JSON line to the per-process log file (fsync'd before a ``kill`` so the
event that explains the death survives it), and (3) stamped into the
flight recorder (stage ``chaos``) so chrome-trace/postmortem reads show
exactly where the schedule struck. ``signature()`` is the
determinism-checkable projection: same seed + same call sequence ⇒
identical signature.
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
from fnmatch import fnmatchcase

from ray_tpu.devtools.chaos.plan import ChaosPlan

# action codes for recorder slots (utils/recorder.py stage CHAOS args)
ACTION_CODES = {"delay": 1, "drop": 2, "duplicate": 3, "error": 4,
                "corrupt": 5, "kill": 6}


class ChaosError(Exception):
    """The injected failure of an ``error`` action. Deliberately a plain
    Exception: it must travel the same handler paths a real fault would."""


class Act:
    """What a fault point's call site must do. ``kind`` is the action
    name; ``payload`` carries the mangled bytes for ``corrupt``."""

    __slots__ = ("kind", "payload")

    def __init__(self, kind: str, payload: bytes | None = None):
        self.kind = kind
        self.payload = payload

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Act({self.kind!r})"


class _CompiledRule:
    __slots__ = ("rule", "index", "rng", "seen", "fired", "is_glob")

    def __init__(self, rule, index: int, seed: int):
        self.rule = rule
        self.index = index
        # per-rule stream: rule order in one plan never perturbs another
        # rule's coin flips
        self.rng = random.Random((seed << 20) ^ (index + 1))
        self.seen = 0
        self.fired = 0
        self.is_glob = any(c in rule.point for c in "*?[")


class ChaosController:
    def __init__(self, plan: ChaosPlan, log_path: str | None = None):
        self.plan = plan
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self._seq = 0
        self._rules = [_CompiledRule(r, i, plan.seed)
                       for i, r in enumerate(plan.rules)]
        # armed-but-idle fast path: a point no rule could ever match
        # returns before taking the lock (immutable structures, so the
        # lock-free read is safe) — hot paths stay sub-µs while armed
        self._exact = frozenset(cr.rule.point for cr in self._rules
                                if not cr.is_glob)
        self._globs = tuple(cr.rule.point for cr in self._rules
                            if cr.is_glob)
        self._log_path = log_path
        self._log_f = open(log_path, "a", buffering=1) if log_path else None

    # ------------------------------------------------------------ evaluation
    def fire(self, name: str, payload: bytes | None, ctx: dict):
        """Evaluate ``name`` against the plan; returns the Act for the
        call site (or None). First matching-and-firing rule wins."""
        if name not in self._exact and not any(
                fnmatchcase(name, g) for g in self._globs):
            return None
        decided = None
        with self._lock:
            for cr in self._rules:
                r = cr.rule
                if cr.is_glob:
                    if not fnmatchcase(name, r.point):
                        continue
                elif r.point != name:
                    continue
                if r.match and any(ctx.get(k) != v
                                   for k, v in r.match.items()):
                    continue
                cr.seen += 1
                if cr.seen <= r.after:
                    continue
                if r.max_fires and cr.fired >= r.max_fires:
                    continue
                if r.every and (cr.seen - r.after) % r.every != 0:
                    continue
                if r.prob is not None and cr.rng.random() >= r.prob:
                    continue
                if r.cluster_once and not self._claim_cluster_once(cr):
                    continue  # another process (or a past fire) owns it
                cr.fired += 1
                self._log_locked(name, r.action, cr.index, ctx)
                # every rng draw stays under the lock so concurrent
                # points can never reorder a rule's seeded stream
                flip_at = (cr.rng.randrange(len(payload))
                           if r.action == "corrupt" and payload else -1)
                decided = (cr, flip_at)
                break
        if decided is None:
            return None
        cr, flip_at = decided
        return self._execute(name, cr, payload, flip_at)

    def _claim_cluster_once(self, cr: _CompiledRule) -> bool:
        """Atomically claim a cluster_once rule's single fire: an O_EXCL
        sentinel in the SHARED chaos log dir (every armed process points
        at the same dir via RT_CHAOS_LOG_DIR), named by the per-run id
        (RT_CHAOS_RUN_ID, stamped at arm time and inherited by every
        child) plus rule index — so log dirs REUSED across runs re-arm
        the rule each run instead of staying disarmed by a stale
        sentinel. Controllers are per-process; without the shared claim
        a shard-loss kill rule would strike every fresh worker a
        recovery retry lands on. No log dir configured -> degrade to
        per-process once (this controller's own fired counter)."""
        if self._log_path is None:
            return cr.fired == 0
        run_id = os.environ.get("RT_CHAOS_RUN_ID", "")
        sentinel = os.path.join(
            os.path.dirname(self._log_path),
            f"once-{run_id}-{cr.index}.fired" if run_id
            else f"once-{cr.index}.fired")
        try:
            os.close(os.open(sentinel, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            return True
        except FileExistsError:
            return False
        except OSError:
            return cr.fired == 0  # unwritable dir: per-process fallback

    def _execute(self, name: str, cr: _CompiledRule,
                 payload: bytes | None, flip_at: int):
        """Side effects, outside the lock."""
        r = cr.rule
        act = r.action
        if act == "delay":
            time.sleep(r.delay_ms / 1e3)
            return None
        if act == "error":
            raise ChaosError(
                f"chaos: injected error at {name} (rule {cr.index})")
        if act == "kill":
            self.close()  # flush: the kill event must survive the kill
            os.kill(os.getpid(), signal.SIGKILL)
            return None  # pragma: no cover - unreachable
        if act == "corrupt":
            if flip_at < 0:
                return Act("corrupt", None)  # no payload: log-only
            mangled = bytearray(payload)
            mangled[flip_at] ^= 0xFF
            return Act("corrupt", bytes(mangled))
        return Act(act)  # drop / duplicate

    # --------------------------------------------------------------- logging
    def _log_locked(self, name: str, action: str, rule_index: int,
                    ctx: dict) -> dict:
        self._seq += 1
        ev = {
            "n": self._seq,
            "pid": os.getpid(),
            "point": name,
            "rule": rule_index,
            "action": action,
            "ts": time.time(),
            "ctx": {k: v for k, v in ctx.items()
                    if isinstance(v, (str, int, float, bool))},
        }
        self.events.append(ev)
        if self._log_f is not None:
            try:
                self._log_f.write(json.dumps(ev) + "\n")
            except (OSError, ValueError):
                # full disk, or close() swapped the file between the None
                # check and the write: chaos must not become a new fault
                pass
        self._record(name, action, rule_index)
        return ev

    def log_external(self, name: str, action: str, ctx: dict) -> None:
        """Log a fault executed outside rule evaluation (killers)."""
        with self._lock:
            self._log_locked(name, action, -1, ctx)

    def _record(self, name: str, action: str, rule_index: int) -> None:
        """Stamp the fired fault into the flight recorder: the 16-byte id
        slot carries the point name, args carry (rule, action, seq)."""
        from ray_tpu.utils import recorder as _rec

        rec = _rec.get_recorder()
        if rec is not None:
            rec.record(name.encode()[:16].ljust(16, b"\0"), _rec.CHAOS,
                       a0=rule_index & 0xFFFFFFFF,
                       a1=ACTION_CODES.get(action, 0), a2=self._seq)

    def signature(self) -> list[tuple]:
        """The deterministic projection of the fault log: (n, point,
        rule, action) per fired fault. Two runs of the same plan seed
        over the same workload must produce identical signatures."""
        with self._lock:
            return [(e["n"], e["point"], e["rule"], e["action"])
                    for e in self.events]

    def close(self) -> None:
        # swap under the lock so no _log_locked writer holds a reference
        # to a file we are about to close (kill-action close() races
        # concurrent fault points on other threads)
        with self._lock:
            f, self._log_f = self._log_f, None
        if f is not None:
            try:
                f.flush()
                os.fsync(f.fileno())
                f.close()
            except (OSError, ValueError):
                pass

"""Process-level chaos: seeded interval/burst raylet- and worker-killers.

The reusable home of what used to live as an inline thread in
``tests/test_resilience.py`` (ref: _private/test_utils.py:1419
ResourceKiller — kill a node/process on a cadence, no goodbyes, while a
workload runs). Raylet kills go through ``Cluster.kill_node`` (SIGKILL
every worker, drop the server, no lease returns, no GCS goodbye) and by
default each loss is RESTORED with a fresh node so cluster capacity
never drains to zero; worker kills SIGKILL a live worker process under
a random raylet, exercising the owner's retry path without losing the
node.

Deterministic: victim selection comes off one ``random.Random(seed)``
stream, so the same seed over the same cluster shape picks the same
victims in the same order. Every kill is appended to ``self.kills`` and
mirrored into the chaos event log when the controller is armed
(``chaos.note``), so killer strikes line up with fault-point events in
``state.list_chaos_events()``.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time

log = logging.getLogger(__name__)


class ProcessKiller:
    """Base killer: every ``interval_s`` pick ``burst`` victims and kill
    them. ``target`` is ``"raylet"`` (hard node loss + optional capacity
    restore) or ``"worker"`` (SIGKILL a leased/idle worker process).
    The head node (``cluster.raylets[0]`` at construction) is protected
    unless ``protect_head=False``."""

    def __init__(self, cluster, *, seed: int = 0, interval_s: float = 2.0,
                 burst: int = 1, target: str = "raylet",
                 restore: bool = True, protect_head: bool = True,
                 max_kills: int = 0):
        if target not in ("raylet", "worker"):
            raise ValueError(f"unknown killer target {target!r}")
        self.cluster = cluster
        self.interval_s = interval_s
        self.burst = burst
        self.target = target
        self.restore = restore
        self.max_kills = max_kills
        self.kills: list[dict] = []
        self._rng = random.Random(seed)
        self._head = cluster.raylets[0] if (protect_head
                                            and cluster.raylets) else None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---------------------------------------------------------------- control
    def start(self) -> "ProcessKiller":
        if self._thread is not None:
            raise RuntimeError("killer already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"chaos-{self.target}-killer")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def __enter__(self) -> "ProcessKiller":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def strike_once(self) -> None:
        """One synchronous seeded strike — progress-paced chaos. A
        wall-clock cadence couples the fault schedule to host speed (a
        loaded box takes N× longer per unit of work, so the same
        interval lands N× more kills per task attempt — the seeded run
        stops being the same experiment); callers that need a
        deterministic schedule strike at workload milestones instead and
        draw victims off the same seeded stream."""
        if self.max_kills and len(self.kills) >= self.max_kills:
            return
        for _ in range(self.burst):
            try:
                if self.target == "raylet":
                    self._kill_raylet()
                else:
                    self._kill_worker()
            except Exception:
                log.debug("killer strike failed", exc_info=True)

    # ------------------------------------------------------------------ loop
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self.max_kills and len(self.kills) >= self.max_kills:
                return
            # chaos races real teardown by design (a victim can die
            # between choice and kill); strike_once skips the strike,
            # never escalates into a test-harness crash
            self.strike_once()

    def _kill_raylet(self) -> None:
        victims = [r for r in self.cluster.raylets if r is not self._head]
        if not victims:
            return
        victim = self._rng.choice(victims)
        cpus = float(victim.ledger.total.get("CPU", 4.0))
        self.cluster.kill_node(victim)
        self._note("raylet", node=victim.node_id.hex())
        if self.restore:
            self.cluster.add_node(num_cpus=cpus)

    def _kill_worker(self) -> None:
        # only READY workers (address set): strangling every worker during
        # startup starves the pool instead of exercising retry paths
        pool = [(r, w) for r in self.cluster.raylets
                for w in r.all_workers.values()
                if w.proc.poll() is None and w.address is not None]
        if not pool:
            return
        raylet, w = self._rng.choice(pool)
        os.kill(w.proc.pid, signal.SIGKILL)
        self._note("worker", node=raylet.node_id.hex(), pid=w.proc.pid,
                   worker=w.worker_id.hex())

    def _note(self, kind: str, **ctx) -> None:
        from ray_tpu.devtools import chaos

        self.kills.append({"ts": time.time(), "target": kind, **ctx})
        if chaos.ENABLED:
            chaos.note(f"killer.{kind}", "kill", **ctx)


class IntervalKiller(ProcessKiller):
    """One victim per interval — the reference ResourceKiller cadence."""

    def __init__(self, cluster, **kw):
        kw.setdefault("burst", 1)
        super().__init__(cluster, **kw)


class BurstKiller(ProcessKiller):
    """Several victims at once per interval: correlated failures (a rack
    loss), the shape single-kill schedules never produce."""

    def __init__(self, cluster, **kw):
        kw.setdefault("burst", 2)
        super().__init__(cluster, **kw)

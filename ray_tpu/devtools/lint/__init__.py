"""raylint — framework-aware static analysis for ray_tpu programs.

AST-based: resolves names through each module's import table so rules fire
on real ray_tpu API usage (`get`/`put`/`wait`/`.remote()`/collectives),
not on look-alike identifiers. Run it as `python -m ray_tpu lint <paths>`.

Rules (see `ray_tpu lint --rules` for rationale):
  RT001 blocking get() inside a remote function/actor method
  RT002 get() in a loop instead of one batched get(refs)
  RT003 .remote() result discarded
  RT004 large np/jnp array passed inline instead of put()
  RT005 mutable default argument on a remote function/actor method
  RT006 collective call order diverging across branches
  RT007 bare except swallowing errors around get()/wait()
  RT008 time.sleep in a remote task without max_retries
  ...
  RT018 wire prefix/flag literal absent from the schema catalog
  RT019 metric constructed inside a hot-path root function
  RT024 whole stream materialized into a list on the request path

The interprocedural pass (`ray_tpu lint --flow`, flow.py) adds
RT020-RT023: it builds a package-wide call graph, infers per-function
effects (blocking / syscall / host-sync / alloc — effects.py), and
reports any forbidden effect REACHABLE from a hot-path root (event-loop
callbacks, fast-lane pumps, tunnel exec paths, serve handlers, jit/scan
regions) with the full call chain. Pre-existing findings live in
`.raylint_baseline.json` so the gate stays adoptable.

Suppress a deliberate finding with `# raylint: disable=RT003  -- reason`
on the offending line, or file-wide with `# raylint: disable-file=RT003`.
"""
from ray_tpu.devtools.lint.engine import (  # noqa: F401
    Finding,
    Rule,
    lint_paths,
    lint_source,
    register,
    rule_table,
    to_json,
)
from ray_tpu.devtools.lint.cli import main  # noqa: F401

"""`ray_tpu lint` CLI: human/JSON output, rule table, exit codes.

Exit codes: 0 no unsuppressed findings, 1 findings reported, 2 bad usage.
"""
from __future__ import annotations

import argparse
import os
import sys

from ray_tpu.devtools.lint import engine

# default target: the installed ray_tpu package itself, not a cwd-relative
# "ray_tpu" that only resolves from the repo root
_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def add_lint_parser(sub):
    """Mount the `lint` subcommand on the top-level ray_tpu CLI."""
    p = sub.add_parser("lint",
                       help="framework-aware static analysis (raylint)")
    p.add_argument("paths", nargs="*", default=[_PACKAGE_ROOT],
                   help="files or directories to lint "
                        "(default: the installed ray_tpu package)")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--select", default=None, metavar="RT001,RT002",
                   help="run only these rules")
    p.add_argument("--ignore", default=None, metavar="RT003",
                   help="skip these rules")
    p.add_argument("--rules", action="store_true",
                   help="print the rule table and exit")
    p.set_defaults(fn=cmd_lint)
    return p


def _split(csv: str | None) -> list[str] | None:
    return [tok.strip() for tok in csv.split(",") if tok.strip()] if csv else None


def cmd_lint(args) -> int:
    import ray_tpu.devtools.lint.rules  # noqa: F401  (populate registry)

    if args.rules:
        if args.format == "json":
            import json

            print(json.dumps(engine.rule_table(), indent=2))
        else:
            for row in engine.rule_table():
                print(f"{row['id']}  {row['summary']}")
                print(f"       {row['rationale']}")
        return 0
    try:
        findings = engine.lint_paths(args.paths,
                                     select=_split(args.select),
                                     ignore=_split(args.ignore))
    except (ValueError, OSError) as e:
        print(f"raylint: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(engine.to_json(findings))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"raylint: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="raylint")
    sub = parser.add_subparsers(dest="cmd", required=True)
    add_lint_parser(sub)
    args = parser.parse_args(sys.argv[1:] if argv is None else argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main(["lint", *sys.argv[1:]]))

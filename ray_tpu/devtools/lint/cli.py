"""`ray_tpu lint` CLI: human/JSON output, rule table, exit codes.

Exit codes: 0 no unsuppressed findings, 1 findings reported, 2 bad usage.
"""
from __future__ import annotations

import argparse
import os
import sys

from ray_tpu.devtools.lint import engine

# default target: the installed ray_tpu package itself, not a cwd-relative
# "ray_tpu" that only resolves from the repo root
_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def add_lint_parser(sub):
    """Mount the `lint` subcommand on the top-level ray_tpu CLI."""
    p = sub.add_parser("lint",
                       help="framework-aware static analysis (raylint)")
    p.add_argument("paths", nargs="*", default=[_PACKAGE_ROOT],
                   help="files or directories to lint "
                        "(default: the installed ray_tpu package)")
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument("--select", default=None, metavar="RT001,RT002",
                   help="run only these rules")
    p.add_argument("--ignore", default=None, metavar="RT003",
                   help="skip these rules")
    p.add_argument("--rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--flow", action="store_true",
                   help="also run the interprocedural pass (RT020-RT023: "
                        "call-graph reachability of blocking/syscall/"
                        "host-sync/alloc effects from hot-path roots)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="flow-finding baseline file (default: "
                        ".raylint_baseline.json in the cwd when present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current flow findings to the baseline "
                        "file and exit 0")
    p.set_defaults(fn=cmd_lint)
    return p


def _split(csv: str | None) -> list[str] | None:
    return [tok.strip() for tok in csv.split(",") if tok.strip()] if csv else None


def cmd_lint(args) -> int:
    import ray_tpu.devtools.lint.rules  # noqa: F401  (populate registry)

    if args.rules:
        if args.format == "json":
            import json

            print(json.dumps(engine.rule_table(), indent=2))
        else:
            for row in engine.rule_table():
                print(f"{row['id']}  {row['summary']}")
                print(f"       {row['rationale']}")
        return 0
    try:
        findings = engine.lint_paths(args.paths,
                                     select=_split(args.select),
                                     ignore=_split(args.ignore))
        if args.flow or args.write_baseline:
            from ray_tpu.devtools.lint import flow

            baseline = args.baseline
            if baseline is None and not args.write_baseline \
                    and os.path.isfile(flow.BASELINE_NAME):
                baseline = flow.BASELINE_NAME
            if args.write_baseline:
                out = args.baseline or flow.BASELINE_NAME
                flow.write_baseline(out, flow.analyze_paths(args.paths))
                print(f"raylint: baseline written to {out}")
                return 0
            flow_findings = flow.analyze_paths(args.paths,
                                               baseline=baseline)
            sel, ign = _split(args.select), _split(args.ignore)
            if sel:
                flow_findings = [f for f in flow_findings
                                 if f.rule_id in sel]
            if ign:
                flow_findings = [f for f in flow_findings
                                 if f.rule_id not in ign]
            findings = sorted(
                findings + flow_findings,
                key=lambda f: (f.path, f.line, f.col, f.rule_id))
    except (ValueError, OSError, KeyError) as e:
        print(f"raylint: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(engine.to_json(findings))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"raylint: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="raylint")
    sub = parser.add_subparsers(dest="cmd", required=True)
    add_lint_parser(sub)
    args = parser.parse_args(sys.argv[1:] if argv is None else argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main(["lint", *sys.argv[1:]]))

"""raylint rules RT001-RT019/RT024 + flow-rule registrations RT020-RT023.

Each AST rule is a Rule subclass registered with @register; hooks
receive (node, ctx) from the engine's single AST walk. See
engine.rule_table() for the ID/summary/rationale table rendered by
`ray_tpu lint --rules`. RT020-RT023 are registered here for the rule
table but fire from the interprocedural pass (flow.py), not from hooks.
"""
from __future__ import annotations

import ast
import os

from ray_tpu.devtools.lint.engine import (
    Context,
    Rule,
    literal_array_size,
    register,
)

# RT004: below this many elements an inline argument is cheap enough that
# copying it into the task spec beats a store round-trip
LARGE_ARRAY_ELEMENTS = 16384  # raylint: disable=RT018 -- array-size threshold, not a wire flag (RT018 sees this file's lazy schema import)


@register
class BlockingGetInRemote(Rule):
    id = "RT001"
    summary = "blocking get() inside a remote function or actor method"
    rationale = ("a task that blocks on get() holds its worker slot while "
                 "waiting on other tasks; under load this deadlocks the "
                 "scheduler (all slots waiting, none running)")

    def on_call(self, node: ast.Call, ctx: Context):
        if ctx.in_remote and ctx.framework_op(node.func) == "get":
            ctx.report(self, node,
                       "ray_tpu.get() blocks inside a remote "
                       f"{ctx.in_remote.kind.replace('_', ' ')}; pass the "
                       "ObjectRef through instead (it resolves on arrival) "
                       "or restructure into a DAG")


@register
class GetInLoop(Rule):
    id = "RT002"
    summary = "get() called once per iteration instead of batched"
    rationale = ("get() in a loop serialises the cluster: each call waits "
                 "for one ref while the rest sit ready; one batched "
                 "get(refs) overlaps all transfers")

    def on_call(self, node: ast.Call, ctx: Context):
        # fires only when the argument references a for-loop/comprehension
        # target: a while-based poll loop, or wait()-then-get-one
        # streaming, is not a loop over refs and stays clean
        if (ctx.framework_op(node.func) == "get"
                and any(ctx.loops_over(arg)
                        for arg in [*node.args,
                                    *[kw.value for kw in node.keywords]])):
            ctx.report(self, node,
                       "get() once per ref inside a loop; collect the refs "
                       "and call get(refs) once (or use wait() for "
                       "streaming)")


@register
class DiscardedRemoteCall(Rule):
    id = "RT003"
    summary = ".remote() result discarded"
    rationale = ("a dropped ObjectRef can never be get() or wait()ed, so "
                 "task errors vanish and backpressure is impossible")

    def on_expr(self, node: ast.Expr, ctx: Context):
        call = node.value
        if (ctx.uses_framework
                and isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "remote"):
            ctx.report(self, node,
                       ".remote() result discarded; keep the ObjectRef "
                       "(even fire-and-forget tasks need their errors "
                       "surfaced via wait())")


@register
class LargeArrayArgument(Rule):
    id = "RT004"
    summary = "large np/jnp array passed inline to .remote() instead of put()"
    rationale = ("inline arguments are copied into every task spec; a "
                 "put() ref is written to the object store once and "
                 "shared zero-copy by every consumer")

    def on_call(self, node: ast.Call, ctx: Context):
        if not (ctx.uses_framework
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "remote"):
            return
        for arg in [*node.args, *[kw.value for kw in node.keywords]]:
            size = literal_array_size(arg, ctx)
            if size is None and isinstance(arg, ast.Name):
                size = ctx.array_bindings.get(arg.id)
            if size is not None and size >= LARGE_ARRAY_ELEMENTS:
                ctx.report(self, arg,
                           f"array of {size} elements passed inline to "
                           ".remote(); put() it once and pass the ref")


@register
class MutableDefaultOnRemote(Rule):
    id = "RT005"
    summary = "mutable default argument on a remote function/actor method"
    rationale = ("the default is evaluated once per worker process and "
                 "shared across invocations, so state leaks between tasks "
                 "on the same worker but not across workers — "
                 "nondeterminism that only appears at scale")

    _MUTABLE_CTORS = {"list", "dict", "set", "bytearray"}

    def _is_mutable(self, node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in self._MUTABLE_CTORS)

    def _check(self, node, ctx: Context):
        for default in [*node.args.defaults,
                        *[d for d in node.args.kw_defaults if d is not None]]:
            if self._is_mutable(default):
                ctx.report(self, default,
                           f"mutable default on remote {node.name}(); use "
                           "None and construct inside the body")

    def on_functiondef(self, node: ast.FunctionDef, ctx: Context):
        if (ctx.remote_decorator(node) is not None
                or getattr(node, "_rt_actor_method", False)):
            self._check(node, ctx)

    on_asyncfunctiondef = on_functiondef


@register
class DivergentCollectiveOrder(Rule):
    id = "RT006"
    summary = "collective call order diverges across branches"
    rationale = ("collectives are rendezvous points: if one replica takes "
                 "the if-branch and another the else, they post different "
                 "op sequences and every participant hangs forever")

    def on_if(self, node: ast.If, ctx: Context):
        if not ctx.in_remote or getattr(node, "_rt006_covered", False):
            return
        body_ops = self._collective_seq(node.body, ctx)
        else_ops = self._collective_seq(node.orelse, ctx)
        if body_ops != else_ops:
            ctx.report(self, node,
                       f"collective sequence diverges across branches "
                       f"({body_ops or 'none'} vs {else_ops or 'none'}); "
                       "hoist the collectives out of the branch or make "
                       "the condition replica-uniform")
            # one finding per divergent chain: the nested ifs (including
            # elifs, which parse as orelse=[If]) lie on the already-
            # reported divergent paths, so their own reports would be
            # duplicates of this one
            for branch in (node.body, node.orelse):
                for stmt in branch:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.If):
                            sub._rt006_covered = True

    def _collective_seq(self, stmts, ctx: Context) -> list[str]:
        ops: list[str] = []
        for stmt in stmts:
            self._collect(stmt, ctx, ops)
        return ops

    def _collect(self, node: ast.AST, ctx: Context, ops: list[str]):
        if isinstance(node, ast.If):
            # the test executes on every path that reaches this if, so
            # collectives in it belong to the enclosing sequence; the
            # branches are their own rendezvous check (on_if visits the
            # nested if too): when they agree the sequence counts once,
            # when they diverge the nested if reports and cascading the
            # outer comparison would only duplicate the finding
            self._collect(node.test, ctx, ops)
            ops.extend(self._collective_seq(node.body, ctx))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # a nested def's body doesn't execute at this point
        if isinstance(node, ast.Call):
            op = ctx.collective_op(node.func)
            if op:
                ops.append(op)
        for child in ast.iter_child_nodes(node):
            self._collect(child, ctx, ops)


@register
class BareExceptAroundGet(Rule):
    id = "RT007"
    summary = "bare except swallowing errors around get()/wait()"
    rationale = ("get() re-raises remote task exceptions; a bare except "
                 "that doesn't re-raise turns a worker crash into silent "
                 "data loss")

    def on_try(self, node, ctx: Context):
        if not self._calls_get_or_wait(node.body, ctx):
            return
        for handler in node.handlers:
            if self._is_catch_all(handler) and not self._reraises(handler):
                ctx.report(self, handler,
                           "bare except around get()/wait(); catch specific "
                           "exceptions or re-raise so remote failures "
                           "propagate")

    on_trystar = on_try

    def _calls_get_or_wait(self, stmts, ctx: Context) -> bool:
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if (isinstance(sub, ast.Call)
                        and ctx.framework_op(sub.func) in ("get", "wait")):
                    return True
        return False

    def _is_catch_all(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        return isinstance(t, ast.Name) and t.id == "BaseException"

    def _reraises(self, handler: ast.ExceptHandler) -> bool:
        return any(isinstance(sub, ast.Raise)
                   for stmt in handler.body for sub in ast.walk(stmt))


@register
class SleepInRemoteWithoutRetry(Rule):
    id = "RT008"
    summary = "time.sleep in a remote function without max_retries"
    rationale = ("a sleeping task pins its worker slot; without "
                 "max_retries a node failure during the sleep loses the "
                 "task silently instead of rescheduling it")

    def on_call(self, node: ast.Call, ctx: Context):
        frame = ctx.in_remote
        if (frame is not None and frame.kind == "task"
                and "max_retries" not in frame.decorator_kwargs
                and ctx.is_time_sleep(node.func)):
            ctx.report(self, node,
                       "time.sleep() in a remote task declared without "
                       "max_retries; add @remote(max_retries=...) or poll "
                       "via wait(timeout=...)")


@register
class OptionsRemoteInLoop(Rule):
    id = "RT009"
    summary = ".options(...).remote(...) inside a loop body"
    rationale = ("each .options() call forks a fresh handle and re-derives "
                 "its submission template (resources, normalized scheduling "
                 "strategy, placement target) per iteration, defeating the "
                 "per-handle template cache; hoist the .options() handle "
                 "out of the loop and call .remote() on it")

    def on_call(self, node: ast.Call, ctx: Context):
        if not ctx.uses_framework or not ctx.loop_depth:
            return
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "remote"):
            return
        inner = f.value
        if (isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Attribute)
                and inner.func.attr == "options"):
            ctx.report(self, node,
                       ".options(...).remote(...) in a loop re-derives a "
                       "submission template every iteration; hoist "
                       "`h = fn.options(...)` above the loop and call "
                       "h.remote() inside it")


@register
class BlockingGetInAsync(Rule):
    id = "RT010"
    summary = "blocking get() inside an async def body"
    rationale = ("ray_tpu.get() blocks its thread until the result lands; "
                 "inside a coroutine that thread IS the event loop, so "
                 "every other coroutine — including the completion "
                 "machinery that would resolve the ref — stalls behind it "
                 "(an async get path exists: await the ref)")

    def on_call(self, node: ast.Call, ctx: Context):
        if ctx.in_async and ctx.framework_op(node.func) == "get":
            ctx.report(self, node,
                       "blocking ray_tpu.get() inside an async def stalls "
                       "the event loop; await the ObjectRef(s) directly "
                       "(or asyncio.gather them) instead")


@register
class SilentExceptionSwallow(Rule):
    id = "RT012"
    summary = "bare `except Exception: pass` (no logging, no re-raise)"
    rationale = ("an except-all whose whole body is `pass` eats every "
                 "failure signal on that path — real faults AND injected "
                 "chaos faults (devtools/chaos) vanish without a trace; "
                 "narrow the handler to the exception the site actually "
                 "expects, or log at debug before swallowing")

    def on_try(self, node, ctx: Context):
        for handler in node.handlers:
            if self._catch_all(handler) and self._only_pass(handler):
                caught = "except" if handler.type is None else \
                    f"except {handler.type.id}"
                ctx.report(self, handler,
                           f"`{caught}: pass` swallows every failure "
                           "silently; catch the specific expected "
                           "exception or log at debug before swallowing")

    on_trystar = on_try

    def _catch_all(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        return isinstance(t, ast.Name) and t.id in ("Exception",
                                                    "BaseException")

    def _only_pass(self, handler: ast.ExceptHandler) -> bool:
        return (len(handler.body) == 1
                and isinstance(handler.body[0], ast.Pass))


@register
class ConstantSleepRetryLoop(Rule):
    id = "RT013"
    summary = "retry loop sleeps a constant with no backoff/jitter"
    rationale = ("a loop that catches a failure and sleeps a fixed "
                 "literal hammers the struggling dependency at a fixed "
                 "cadence: every caller retries in lockstep (synchronized "
                 "herd) and the interval never widens to let the fault "
                 "clear; compute the delay from the attempt number "
                 "(exponential backoff) and jitter it")

    _SLEEPS = {("time", "sleep"), ("asyncio", "sleep")}

    def on_try(self, node, ctx: Context):
        # fires on the canonical retry shape: a try INSIDE a loop whose
        # except handler sleeps a literal constant. Sleeps on the loop's
        # normal path (polling) are deliberate pacing, not retry backoff,
        # and stay clean.
        if not ctx.loop_depth:
            return
        for handler in node.handlers:
            seen: set[int] = set()  # an awaited sleep walks as Await AND Call
            for stmt in handler.body:
                for sub in ast.walk(stmt):
                    call = sub.value if isinstance(sub, ast.Await) else sub
                    if id(call) in seen:
                        continue
                    seen.add(id(call))
                    if (isinstance(call, ast.Call)
                            and ctx.imports.resolve(call.func) in self._SLEEPS
                            and call.args
                            and isinstance(call.args[0], ast.Constant)
                            and isinstance(call.args[0].value, (int, float))):
                        ctx.report(self, call,
                                   "retry loop sleeps a constant "
                                   f"{call.args[0].value!r}s on failure; "
                                   "derive the delay from the attempt "
                                   "number (exponential backoff) and add "
                                   "jitter so retries neither hammer nor "
                                   "synchronize")

    on_trystar = on_try


_METRIC_CTORS = {"Counter", "Gauge", "Histogram"}


@register
class MetricConstructedPerCall(Rule):
    id = "RT011"
    summary = "Counter/Gauge/Histogram constructed inside a function or loop body"
    rationale = ("every metric construction registers in the process-wide "
                 "registry under its name: per-call construction churns "
                 "the registry (the old object with its accumulated "
                 "values is silently replaced and its history lost) and "
                 "leaks a dict entry per unique name; metrics are "
                 "module-level singletons by design")

    def on_call(self, node: ast.Call, ctx: Context):
        if not ctx.func_depth and not ctx.loop_depth:
            return
        origin = ctx.imports.resolve(node.func)
        if (origin and origin[0] == "ray_tpu"
                and origin[-1] in _METRIC_CTORS
                and "metrics" in origin[:-1]):
            where = "loop" if ctx.loop_depth else "function"
            ctx.report(self, node,
                       f"{origin[-1]}(...) constructed in a {where} body "
                       "re-registers in the global metrics registry every "
                       "call (accumulated values silently reset); hoist "
                       "the metric to module level")


@register
class MetricConstructedOnHotPath(Rule):
    id = "RT019"
    summary = "Counter/Gauge/Histogram constructed inside a hot-path root function"
    rationale = ("the rollup plane's per-task budget (<1µs, the "
                 "metrics_overhead_us bench arm) assumes hot paths only "
                 "touch pre-built metric cells; constructing a metric "
                 "inside a fast-lane pump, tunnel exec path, or serve "
                 "handler takes the registry lock and churns the name "
                 "table once per record — RT011's per-call class, but on "
                 "the paths where it costs throughput, caught without "
                 "the --flow pass")

    def on_call(self, node: ast.Call, ctx: Context):
        name = ctx.func_name
        if name is None:
            return
        from ray_tpu.devtools.lint.effects import NAMED_ROOTS

        root_kind = NAMED_ROOTS.get(name)
        if root_kind is None:
            return
        origin = ctx.imports.resolve(node.func)
        if (origin and origin[0] == "ray_tpu"
                and origin[-1] in _METRIC_CTORS
                and "metrics" in origin[:-1]):
            ctx.report(self, node,
                       f"{origin[-1]}(...) constructed inside {name}() — a "
                       f"{root_kind} root: metrics are module-level "
                       "singletons; hot paths must only inc()/observe() "
                       "pre-built cells (per-record construction blows the "
                       "<1µs/task metrics budget)")


_SHARDED_PRODUCERS = {"put_sharded", "reshard"}


@register
class ShardedRefMaterializedOnDriver(Rule):
    id = "RT014"
    summary = "driver-side materialization of a ShardedObjectRef"
    rationale = ("a ShardedObjectRef is a manifest of per-host shm "
                 "shards; ray_tpu.get()/np.asarray() on one outside a "
                 "worker gathers every shard's bytes through this one "
                 "process — exactly the driver funnel the sharded plane "
                 "exists to avoid; use get_sharded() (device-local "
                 "assembly) or pass the ref to a @remote(in_specs=...) "
                 "task so shards stay on their nodes")

    def __init__(self):
        self._sharded: set[str] = set()

    def on_functiondef(self, node: ast.FunctionDef, ctx: Context):
        # per-function scope: a name bound from put_sharded in one
        # function must not taint a same-named parameter or binding in
        # a later function (the engine's array_bindings save/restore
        # idiom, done rule-locally; nested defs trade a rare false
        # negative for no false positives)
        self._sharded.clear()

    on_asyncfunctiondef = on_functiondef

    def on_assign(self, node: ast.Assign, ctx: Context):
        # simple forward flow: names bound from put_sharded()/reshard()
        # calls (resolved through the import table, so rt.put_sharded,
        # ray_tpu.sharded.reshard and bare imports all count) are
        # ShardedObjectRefs until rebound
        if len(node.targets) != 1 or not isinstance(node.targets[0],
                                                    ast.Name):
            return
        name = node.targets[0].id
        if isinstance(node.value, ast.Call):
            origin = ctx.imports.resolve(node.value.func)
            if (origin and origin[0] == "ray_tpu"
                    and origin[-1] in _SHARDED_PRODUCERS):
                self._sharded.add(name)
                return
        self._sharded.discard(name)

    def on_call(self, node: ast.Call, ctx: Context):
        if not self._sharded or ctx.in_remote:
            return  # inside a task/actor method the shards ARE local
        op = ctx.framework_op(node.func)
        numpy_op = ctx.is_numpy_ctor(node.func)
        if op != "get" and numpy_op not in ("asarray", "array"):
            return
        for arg in node.args:
            if isinstance(arg, ast.Name) and arg.id in self._sharded:
                fn = ("ray_tpu.get" if op == "get"
                      else f"np.{numpy_op}")
                ctx.report(self, node,
                           f"{fn}({arg.id}) materializes a "
                           "ShardedObjectRef on the driver (every shard "
                           "funnels through this process); use "
                           "get_sharded() for device-local assembly or "
                           "consume it in a @remote(in_specs=...) task")
                return


@register
class BatchQueueConfiguredPerCall(Rule):
    id = "RT015"
    summary = ("serve.batch configured inside a request-path function "
               "body")
    rationale = ("@serve.batch builds ONE coalescing queue per wrapped "
                 "function: applying it (or calling serve.batch(fn, "
                 "max_batch_size=..., batch_wait_timeout_s=...)) inside "
                 "a handler body re-creates the wrapper — and therefore "
                 "a fresh empty queue — on every request, so no two "
                 "requests ever share a queue and batching silently "
                 "degenerates to batch-size-1 calls; declare the "
                 "batched method at class/module level")

    #: one-time setup bodies: building a batch wrapper here (e.g. with
    #: instance-derived knobs) creates ONE queue for the object's
    #: lifetime — the llm.serving LLMServer shape — not one per request
    _SETUP_FNS = ("__init__", "__post_init__", "reconfigure")

    def on_call(self, node: ast.Call, ctx: Context):
        # decorators/defaults are walked in the ENCLOSING scope (see
        # engine._walk_function), so a class-level @serve.batch(...) on
        # a method sits at func_depth 0 and stays clean; only a call
        # evaluated inside some function body — per request — fires
        if not ctx.func_depth or ctx.func_name in self._SETUP_FNS:
            return
        origin = ctx.imports.resolve(node.func)
        if not (origin and origin[0] == "ray_tpu" and origin[-1] == "batch"
                and ("serve" in origin[:-1] or "batching" in origin[:-1])):
            return
        knobs = [kw.arg for kw in node.keywords
                 if kw.arg in ("max_batch_size", "batch_wait_timeout_s")]
        detail = (f" (with {', '.join(knobs)} literals)"
                  if knobs and all(
                      isinstance(kw.value, ast.Constant)
                      for kw in node.keywords if kw.arg in knobs)
                  else "")
        ctx.report(self, node,
                   "serve.batch(...) evaluated inside a function body"
                   f"{detail} re-creates the batch queue per call, "
                   "defeating request coalescing; hoist the batched "
                   "method to class/module level")


@register
class HostSyncInDecodeLoop(Rule):
    id = "RT017"
    summary = ("host-device sync inside a request-path loop body")
    rationale = ("the fused-scan decode loop exists to keep K steps on "
                 "device per host round trip; a block_until_ready() or "
                 "np.asarray()/float()/int() on a device array inside "
                 "the loop body forces a dispatch-sync-dispatch pattern "
                 "that serializes the pipeline — one sync per ITERATION "
                 "where the engine budget is one per BLOCK. Sync once "
                 "after the loop (or per coalesced block, like "
                 "_emit_spec_block's single np.asarray), and keep the "
                 "(token, position) carry on device between dispatches")

    def __init__(self):
        self._device: set[str] = set()

    def on_functiondef(self, node: ast.FunctionDef, ctx: Context):
        # per-function forward flow, the RT014 binding idiom: names
        # bound from jax-origin calls are device arrays until rebound
        self._device.clear()

    on_asyncfunctiondef = on_functiondef

    def _uses_jax(self, ctx: Context) -> bool:
        return any(origin and origin[0] == "jax"
                   for origin in ctx.imports.bindings.values())

    def on_assign(self, node: ast.Assign, ctx: Context):
        if len(node.targets) != 1 or not isinstance(node.targets[0],
                                                    ast.Name):
            return
        name = node.targets[0].id
        if isinstance(node.value, ast.Call):
            origin = ctx.imports.resolve(node.value.func)
            if origin and origin[0] == "jax":
                self._device.add(name)
                return
        self._device.discard(name)

    def on_call(self, node: ast.Call, ctx: Context):
        if not ctx.loop_depth:
            return
        # leg 1: .block_until_ready() — a device-array method (and the
        # jax.block_until_ready free function); the attribute form is
        # unresolvable through imports, so gate on the module actually
        # importing jax to keep unrelated code clean
        func = node.func
        if ((isinstance(func, ast.Attribute)
             and func.attr == "block_until_ready"
             and self._uses_jax(ctx))
                or ctx.imports.resolve(func) == ("jax",
                                                 "block_until_ready")):
            ctx.report(self, node,
                       "block_until_ready() in a loop body syncs the "
                       "host to the device every iteration; sync once "
                       "per fused block (or after the loop) instead")
            return
        # leg 2: host materialization of a name bound from a jax call —
        # np.asarray/np.array (the NUMPY root; jnp.asarray stays on
        # device) or the float()/int() builtins
        if not self._device:
            return
        origin = ctx.imports.resolve(func)
        numpy_op = (origin[-1] if origin and origin[0] == "numpy"
                    and origin[-1] in ("asarray", "array") else None)
        builtin = (func.id if isinstance(func, ast.Name)
                   and func.id in ("float", "int")
                   and ctx.imports.resolve(func) is None else None)
        if numpy_op is None and builtin is None:
            return
        for arg in node.args:
            if isinstance(arg, ast.Name) and arg.id in self._device:
                fn = f"np.{numpy_op}" if numpy_op else f"{builtin}"
                ctx.report(self, node,
                           f"{fn}({arg.id}) on a device array in a loop "
                           "body is a host-device sync per iteration — "
                           "the fused-scan throughput killer; batch the "
                           "transfer once per block/after the loop")
                return


@register
class SpanContextRederivedInLoop(Rule):
    id = "RT016"
    summary = ("fresh trace context constructed inside a request-path "
               "loop body")
    rationale = ("tracing.span(name, None, ...) / tracing.inject() / "
                 "tracing.submit_context() START a trace when no context "
                 "is given: inside a loop body each iteration mints a "
                 "NEW root (fresh trace_id, fresh head-sampling draw), "
                 "so one logical request shatters into N single-span "
                 "traces the assembler can never stitch — the RT011 "
                 "metric-in-loop shape, applied to spans. Capture the "
                 "context ONCE outside the loop (tracing.current() / "
                 "submit_context()) and pass it to every per-item span, "
                 "the way the worker pumps batch-stamp their records")

    def on_call(self, node: ast.Call, ctx: Context):
        if not ctx.loop_depth:
            return
        origin = ctx.imports.resolve(node.func)
        if not (origin and origin[0] == "ray_tpu"
                and "tracing" in origin[:-1]):
            return
        leaf = origin[-1]
        if leaf in ("inject", "submit_context"):
            ctx.report(self, node,
                       f"tracing.{leaf}() in a loop body re-derives the "
                       "trace context per iteration (a fresh ROOT trace "
                       "each time the contextvar is unset); hoist the "
                       "capture above the loop and reuse it")
            return
        if leaf != "span":
            return
        # the trace_ctx argument (2nd positional): missing or a literal
        # None means "start a fresh trace here" — per iteration
        tc = node.args[1] if len(node.args) >= 2 else None
        if tc is None:
            for kw in node.keywords:
                if kw.arg == "trace_ctx":
                    tc = kw.value
        if tc is None or (isinstance(tc, ast.Constant) and tc.value is None):
            ctx.report(self, node,
                       "tracing.span(...) opened in a loop body without "
                       "a trace context starts a NEW trace per "
                       "iteration; capture the parent context once "
                       "outside the loop and pass it explicitly")


# ---------------------------------------------------- RT018: schema drift
# the wire-bearing core modules: a raw record-prefix / status-flag literal
# in these files (or any file importing the fastpath/tunnel/schema
# modules) must exist in utils/schema.py's catalogs, or it is the PR
# 10/11 shipped-but-uncataloged bug class
_WIRE_FILES = {"fastpath.py", "tunnel.py", "worker.py", "raylet.py",
               "core_client.py"}
_WIRE_IMPORTS = {("ray_tpu", "core", "fastpath"),
                 ("ray_tpu", "core", "tunnel"),
                 ("ray_tpu", "utils", "schema")}
# candidate flag literals: power-of-two ints in the reply-flag byte range
_FLAG_LO, _FLAG_HI = 0x100, 0x8000

_catalog_cache: tuple | None = None


def _wire_catalog() -> tuple:
    """(prefix chars, flag values) from utils/schema.py — imported lazily
    (pure-data module) so the linter stays importable standalone."""
    global _catalog_cache
    if _catalog_cache is None:
        from ray_tpu.utils import schema

        _catalog_cache = (
            frozenset(schema.RECORD_PREFIXES),
            frozenset(f["value"] for f in schema.RECORD_FLAGS.values()),
        )
    return _catalog_cache


def _is_prefix_literal(node: ast.AST) -> str | None:
    """The single-uppercase-ASCII bytes literal shape (b"Q") wire record
    prefixes are written as."""
    if (isinstance(node, ast.Constant) and isinstance(node.value, bytes)
            and len(node.value) == 1 and node.value.isalpha()
            and node.value.isupper()):
        return node.value.decode("ascii")
    return None


@register
class WireSchemaLiteralDrift(Rule):
    id = "RT018"
    summary = ("wire record prefix / status-flag literal absent from the "
               "utils/schema.py catalog")
    rationale = ("every record prefix byte and reply status flag on the "
                 "wire must be cataloged in schema.RECORD_PREFIXES / "
                 "RECORD_FLAGS — the catalog is what test_wire_schema.py "
                 "machine-checks against the native header, so an "
                 "uncataloged literal ships a wire entry the version "
                 "gate and the docs never heard of (PRs 10 and 11 each "
                 "shipped one and paid a debugging cycle); add the "
                 "catalog row in the same commit as the literal")

    def __init__(self):
        self._scoped: bool | None = None

    def _in_scope(self, ctx: Context) -> bool:
        if self._scoped is None:
            parts = os.path.normpath(ctx.path).split(os.sep)
            self._scoped = (
                (len(parts) >= 2 and parts[-2] == "core"
                 and parts[-1] in _WIRE_FILES)
                or any(origin[:3] in _WIRE_IMPORTS
                       for origin in ctx.imports.bindings.values()))
        return self._scoped

    def _check_prefix(self, node: ast.AST, ctx: Context):
        ch = _is_prefix_literal(node)
        if ch is None:
            return
        prefixes, _ = _wire_catalog()
        if ch not in prefixes:
            ctx.report(self, node,
                       f'record prefix b"{ch}" is not in '
                       "schema.RECORD_PREFIXES — catalog the new record "
                       "type (with its since-version) before it ships")

    # prefix bytes appear in frame construction (b"Q" + header + body)
    def on_binop(self, node: ast.BinOp, ctx: Context):
        if not self._in_scope(ctx):
            return
        if isinstance(node.op, ast.Add):
            for side in (node.left, node.right):
                self._check_prefix(side, ctx)
            return
        # flag literals appear in bitwise composition (status | 0x800)
        if isinstance(node.op, (ast.BitOr, ast.BitAnd)):
            for side in (node.left, node.right):
                self._check_flag_literal(side, ctx)

    def _check_flag_literal(self, node: ast.AST, ctx: Context):
        if (isinstance(node, ast.Constant) and isinstance(node.value, int)
                and _FLAG_LO <= node.value <= _FLAG_HI
                and node.value & (node.value - 1) == 0
                and node.value not in _wire_catalog()[1]):
            ctx.report(self, node,
                       f"status flag {node.value:#x} is not in "
                       "schema.RECORD_FLAGS — catalog the flag "
                       "(value + since-version) before it ships")

    # ...and in augmented form (status |= 0x800, status &= 0x800)
    def on_augassign(self, node: ast.AugAssign, ctx: Context):
        if not self._in_scope(ctx):
            return
        if isinstance(node.op, (ast.BitOr, ast.BitAnd)):
            self._check_flag_literal(node.value, ctx)

    # ...and in dispatch (kind == b"Q", kind in (b"A", b"C"))
    def on_compare(self, node: ast.Compare, ctx: Context):
        if not self._in_scope(ctx):
            return
        for comp in (node.left, *node.comparators):
            self._check_prefix(comp, ctx)
            if isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                for elt in comp.elts:
                    self._check_prefix(elt, ctx)

    # module-level NAMED_FLAG = 0x800 defining an uncataloged flag
    def on_assign(self, node: ast.Assign, ctx: Context):
        if not self._in_scope(ctx) or ctx.func_depth:
            return
        if len(node.targets) != 1 or not isinstance(node.targets[0],
                                                    ast.Name):
            return
        name = node.targets[0].id
        if not (name.isupper() and not name.startswith("_")):
            return
        v = node.value
        if (isinstance(v, ast.Constant) and isinstance(v.value, int)
                and _FLAG_LO <= v.value <= _FLAG_HI
                and v.value & (v.value - 1) == 0):
            _, flags = _wire_catalog()
            if v.value not in flags:
                ctx.report(self, node,
                           f"{name} = {v.value:#x} defines a status flag "
                           "absent from schema.RECORD_FLAGS — catalog it "
                           "(value + since-version) in the same commit")


# stream producers: attribute calls that return an incremental stream —
# the handle-level planes (.stream() per-item refs, .stream_chunks() "G"
# chunk records, .stream_deltas() producer) and the router legs beneath
# them. The attribute shape is unresolvable through imports (the receiver
# is a handle in a local), so RT024 gates on uses_framework like RT003.
_STREAM_PRODUCERS = {"stream", "stream_chunks", "stream_deltas",
                     "route_streaming", "route_streaming_async",
                     "route_stream_chunks"}


@register
class WholeStreamMaterialized(Rule):
    id = "RT024"
    summary = ("whole stream materialized into a list inside a function "
               "body")
    rationale = ("the streaming plane exists so chunks reach the consumer "
                 "as they are produced — TTFC tracks the FIRST decode "
                 "block and memory stays one chunk deep; `[x async for x "
                 "in stream]` or `list(stream)` buffers every chunk "
                 "before the caller sees one, so time-to-first-chunk "
                 "silently becomes total generation latency and the "
                 "buffer grows with max_tokens — a unary call with "
                 "streaming overhead; consume incrementally (async for) "
                 "or call the unary method")

    def __init__(self):
        self._streams: set[str] = set()

    def on_functiondef(self, node: ast.FunctionDef, ctx: Context):
        # per-function forward flow, the RT014 binding idiom: names bound
        # from stream-producer calls are streams until rebound
        self._streams.clear()

    on_asyncfunctiondef = on_functiondef

    def _is_producer(self, node: ast.AST, ctx: Context) -> bool:
        return (ctx.uses_framework
                and isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _STREAM_PRODUCERS)

    def on_assign(self, node: ast.Assign, ctx: Context):
        if len(node.targets) != 1 or not isinstance(node.targets[0],
                                                    ast.Name):
            return
        name = node.targets[0].id
        if self._is_producer(node.value, ctx):
            self._streams.add(name)
        else:
            self._streams.discard(name)

    def _check_source(self, it: ast.AST, node: ast.AST, how: str,
                      ctx: Context) -> bool:
        if isinstance(it, ast.Name) and it.id in self._streams:
            src = it.id
        elif self._is_producer(it, ctx):
            src = f".{it.func.attr}(...)"
        else:
            return False
        ctx.report(self, node,
                   f"{how} over the stream {src} buffers every chunk "
                   "before the caller sees the first one (TTFC becomes "
                   "total latency, memory grows with the generation); "
                   "consume it incrementally with `async for` / `for`, "
                   "or use the unary method")
        return True

    def on_listcomp(self, node, ctx: Context):
        if not ctx.func_depth:
            return
        for gen in node.generators:
            if self._check_source(gen.iter, node, "a comprehension", ctx):
                return

    on_setcomp = on_listcomp

    def on_call(self, node: ast.Call, ctx: Context):
        func = node.func
        if (not ctx.func_depth or not node.args
                or not (isinstance(func, ast.Name) and func.id == "list"
                        and ctx.imports.resolve(func) is None)):
            return
        self._check_source(node.args[0], node, "list()", ctx)


# ------------------------------------------- RT020-RT023: flow-pass rules
# Registered so `--rules` documents them and select/ignore validate, but
# they carry no on_* hooks: findings come from the interprocedural pass
# (ray_tpu.devtools.lint.flow, `ray_tpu lint --flow`), which reports the
# full root -> ... -> effect-site call chain per finding.
@register
class BlockingReachableFromHotRoot(Rule):
    id = "RT020"
    summary = ("blocking call reachable from an event-loop / hot-path "
               "root (flow pass)")
    rationale = ("a sleep, lock-wait, blocking get, file/socket read, or "
                 "subprocess wait anywhere in the call graph of an event-"
                 "loop callback or fast-lane pump parks the thread every "
                 "other callback shares — the PR 9 class, where one "
                 "blocking shm read on the default executor deadlocked "
                 "the whole process; RT001/RT010 catch the textually-"
                 "local case, this rule catches it any number of helper "
                 "hops away")


@register
class SyscallReachableFromHotRoot(Rule):
    id = "RT021"
    summary = ("per-call syscall reachable from a fast-lane / serve root "
               "(flow pass)")
    rationale = ("os.urandom / getpid / uuid4 / secrets cost a syscall "
                 "per invocation: on the submit fast path or a serve "
                 "handler that is a fixed per-record tax (PR 8/11 "
                 "measured ~288µs of urandom per request) — hoist the "
                 "entropy/identity read out of the hot path or cache it "
                 "per worker")


@register
class HostSyncReachableFromJitRegion(Rule):
    id = "RT022"
    summary = ("host-device sync reachable from a jit/scan-traced region "
               "(flow pass)")
    rationale = ("block_until_ready / device_get / np.asarray / float() "
                 "on a jax value reachable from a function traced by "
                 "jax.jit or lax.scan serializes the fused dispatch into "
                 "per-step round-trips — RT017's idiom (the PR 14 decode-"
                 "loop regression) generalized across helper calls")


@register
class AllocReachableFromHotRoot(Rule):
    id = "RT023"
    summary = ("registry-churning construction reachable from a hot root "
               "(flow pass)")
    rationale = ("metrics Counter/Gauge/Histogram, fresh trace roots, "
                 "serve.batch wrappers, and queue objects are build-once "
                 "objects: constructing one anywhere under a fast-lane "
                 "pump or serve handler churns registries and allocators "
                 "per record — the RT011/RT015/RT016 class, caught "
                 "through call hops")

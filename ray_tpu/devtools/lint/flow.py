"""raylint interprocedural pass: call-graph coloring for hot-path effects.

RT001-RT017 catch hot-path anti-patterns only when the offending call
sits textually inside the hot function — one helper hop and they are
blind. This pass closes that hole:

  1. a package-wide call graph over the modules being linted — direct
     calls, method calls (class-attribute resolution: `self.meth`,
     `self.attr.meth` via `self.attr = ClassName(...)` in any method,
     local `x = ClassName(...)` forward flow, inheritance walk),
     asyncio callback edges (call_soon/_threadsafe/call_later,
     create_task/ensure_future), executor-submit edges
     (run_in_executor/submit, the default executor distinguished from
     private pools), thread targets, functools.partial unwrapping, and
     `fn.remote()` dispatch edges;
  2. effect inference per function (effects.EffectScanner) propagated
     to fixpoint through the graph, each edge kind masking the effects
     that traverse it (effects.EDGE_MASKS);
  3. context roots coloring the graph — named hot functions
     (effects.NAMED_ROOTS), every call_soon-family callback, and every
     function traced by jax.jit / lax.scan|while_loop|fori_loop — each
     with a forbidden-effect set (effects.ROOT_FORBIDS).

A finding (RT020-RT023) fires when a forbidden effect is REACHABLE from
a colored root, and reports the full call chain root -> ... -> effect
site. Findings anchor at the effect site (the line you fix or
`# raylint: disable=RT02x` — the engine's per-line suppressions apply),
and carry a line-stable key `rule:sink_qualname:detail` consumed by the
`.raylint_baseline.json` mechanism so the self-check gate stays
adoptable as the package grows.
"""
from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

from ray_tpu.devtools.lint import effects as fx
from ray_tpu.devtools.lint.engine import (
    Finding,
    iter_python_files,
    parse_suppressions,
)

_CALL_SOON = {"call_soon", "call_soon_threadsafe"}
_CALL_LATER = {"call_later", "call_at"}
_TASK_CTORS = {"create_task", "ensure_future"}
_JIT_WRAPPERS = {("jax", "jit"), ("jax", "pmap")}
# (origin suffix, index of the body-function argument)
_TRACED_LOOPS = {("lax", "scan"): 0, ("lax", "while_loop"): 1,
                 ("lax", "fori_loop"): 2}


# ------------------------------------------------------------- module model
class ModuleImports:
    """engine.ImportTable semantics plus relative-import resolution: the
    engine stays silent on `from . import api` (origin unknown for a
    lone file), but the flow pass knows each module's dotted name, so
    in-package relative imports resolve to absolute origins."""

    def __init__(self, module_parts: tuple, is_package: bool):
        self.bindings: dict[str, tuple] = {}
        self._pkg = module_parts if is_package else module_parts[:-1]

    def collect(self, tree: ast.AST):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    parts = tuple(alias.name.split("."))
                    if alias.asname:
                        self.bindings[alias.asname] = parts
                    else:
                        self.bindings[parts[0]] = parts[:1]
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self._pkg
                    if node.level > 1:
                        cut = len(base) - (node.level - 1)
                        if cut < 0:
                            continue
                        base = base[:cut]
                    if node.module:
                        base = base + tuple(node.module.split("."))
                elif node.module:
                    base = tuple(node.module.split("."))
                else:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.bindings[alias.asname or alias.name] = \
                        base + (alias.name,)

    def resolve(self, node: ast.AST):
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        origin = self.bindings.get(node.id)
        if origin is None:
            return None
        return origin + tuple(reversed(parts))


@dataclass
class FuncInfo:
    qualname: str            # fully dotted: "pkg.mod:Class.meth"
    local_name: str          # leaf name
    module: "ModuleInfo"
    node: ast.AST
    path: str
    line: int
    is_async: bool
    class_name: str | None = None
    edges: list = field(default_factory=list)     # list[CallEdge]
    sites: list = field(default_factory=list)     # list[fx.EffectSite]
    root_kind: str | None = None
    root_cause: str = ""     # how it got colored, for finding messages


@dataclass
class CallEdge:
    caller: FuncInfo
    callee: FuncInfo
    kind: str    # key into effects.EDGE_MASKS
    line: int    # call-site line in the caller's file


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    bases: list = field(default_factory=list)        # raw origin tuples
    methods: dict = field(default_factory=dict)      # name -> FuncInfo
    attr_classes: dict = field(default_factory=dict)  # self.X -> origin


@dataclass
class ModuleInfo:
    name: str                # dotted
    path: str
    imports: ModuleImports = None
    functions: dict = field(default_factory=dict)    # local qualname -> Func
    classes: dict = field(default_factory=dict)      # name -> ClassInfo
    uses_jax: bool = False


def _module_name_parts(path: str) -> tuple[tuple, bool]:
    """Dotted module parts for a file, walking up through __init__.py
    package markers; (parts, is_package)."""
    path = os.path.abspath(path)
    base = os.path.basename(path)
    is_pkg = base == "__init__.py"
    parts = [] if is_pkg else [os.path.splitext(base)[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if not parts:  # a bare __init__.py with no package parent
        parts = [os.path.splitext(base)[0]]
    return tuple(reversed(parts)), is_pkg


# ---------------------------------------------------------------- the graph
class CallGraph:
    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FuncInfo] = {}   # fully dotted qualname
        self.roots: list[FuncInfo] = []

    # -- construction -------------------------------------------------------
    @classmethod
    def build(cls, paths) -> "CallGraph":
        g = cls()
        for fp in iter_python_files(paths):
            g._index_file(fp)
        g._seed_attr_classes()
        for mod in g.modules.values():
            g._collect_edges_and_effects(mod)
        g._finish_roots()
        return g

    def _seed_attr_classes(self):
        """self.X = ClassName(...) in any method registers X's class on
        the owning ClassInfo, enabling `self.X.meth()` resolution."""
        for mod in self.modules.values():
            for ci in mod.classes.values():
                for meth in ci.methods.values():
                    for sub in ast.walk(meth.node):
                        if not isinstance(sub, ast.Assign) \
                                or len(sub.targets) != 1:
                            continue
                        t = sub.targets[0]
                        if not (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and isinstance(sub.value, ast.Call)):
                            continue
                        fn2 = sub.value.func
                        target = None
                        if isinstance(fn2, ast.Name) \
                                and fn2.id in mod.classes:
                            target = mod.classes[fn2.id]
                        else:
                            origin = mod.imports.resolve(fn2)
                            if origin:
                                target = self.resolve_class(origin)
                        if target is not None \
                                and t.attr not in ci.attr_classes:
                            ci.attr_classes[t.attr] = target

    def _index_file(self, path: str):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return  # the AST pass already reports RT000
        parts, is_pkg = _module_name_parts(path)
        mod = ModuleInfo(name=".".join(parts), path=path)
        mod.imports = ModuleImports(parts, is_pkg)
        mod.imports.collect(tree)
        mod.uses_jax = any(o and o[0] == "jax"
                           for o in mod.imports.bindings.values())
        self.modules[mod.name] = mod
        self._index_scope(mod, tree.body, prefix="", class_name=None)

    def _index_scope(self, mod: ModuleInfo, stmts, prefix: str,
                     class_name: str | None):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local = prefix + stmt.name
                fn = FuncInfo(
                    qualname=f"{mod.name}:{local}", local_name=stmt.name,
                    module=mod, node=stmt, path=mod.path, line=stmt.lineno,
                    is_async=isinstance(stmt, ast.AsyncFunctionDef),
                    class_name=class_name)
                mod.functions[local] = fn
                self.functions[fn.qualname] = fn
                if class_name and prefix == f"{class_name}.":
                    mod.classes[class_name].methods[stmt.name] = fn
                self._check_jit_decorators(mod, fn)
                # nested defs: their own nodes, one more prefix level
                self._index_scope(mod, stmt.body,
                                  prefix=local + ".<locals>.",
                                  class_name=None)
            elif isinstance(stmt, ast.ClassDef) and class_name is None \
                    and not prefix:
                ci = ClassInfo(name=stmt.name, module=mod)
                for b in stmt.bases:
                    origin = mod.imports.resolve(b)
                    if origin is None and isinstance(b, ast.Name):
                        origin = tuple(mod.name.split(".")) + (b.id,)
                    if origin:
                        ci.bases.append(origin)
                mod.classes[stmt.name] = ci
                self._index_scope(mod, stmt.body, prefix=f"{stmt.name}.",
                                  class_name=stmt.name)

    def _check_jit_decorators(self, mod: ModuleInfo, fn: FuncInfo):
        for deco in getattr(fn.node, "decorator_list", []):
            target = deco.func if isinstance(deco, ast.Call) else deco
            origin = mod.imports.resolve(target)
            if origin and tuple(origin[-2:]) in _JIT_WRAPPERS:
                self._color(fn, "jit-region", "@jit decorator")
            elif (isinstance(deco, ast.Call)
                  and origin and origin[-1] == "partial" and deco.args):
                inner = mod.imports.resolve(deco.args[0])
                if inner and tuple(inner[-2:]) in _JIT_WRAPPERS:
                    self._color(fn, "jit-region", "@partial(jit) decorator")

    def _color(self, fn: FuncInfo, kind: str, cause: str):
        if fn.root_kind is None:
            fn.root_kind = kind
            fn.root_cause = cause

    # -- cross-module resolution --------------------------------------------
    def resolve_func(self, origin, depth: int = 0) -> FuncInfo | None:
        """Origin tuple -> FuncInfo, chasing package __init__ re-exports."""
        if not origin or depth > 6:
            return None
        for i in range(len(origin) - 1, 0, -1):
            mod = self.modules.get(".".join(origin[:i]))
            if mod is None:
                continue
            rest = origin[i:]
            if len(rest) == 1:
                if rest[0] in mod.functions:
                    return mod.functions[rest[0]]
                if rest[0] in mod.classes:
                    return self.lookup_method(mod.classes[rest[0]],
                                              "__init__")
            elif len(rest) == 2 and rest[0] in mod.classes:
                return self.lookup_method(mod.classes[rest[0]], rest[1])
            # re-export chase through this module's import table
            tgt = mod.imports.bindings.get(rest[0])
            if tgt and tgt != origin:
                return self.resolve_func(tgt + rest[1:], depth + 1)
            return None
        return None

    def resolve_class(self, origin, depth: int = 0) -> ClassInfo | None:
        if not origin or depth > 6:
            return None
        for i in range(len(origin) - 1, 0, -1):
            mod = self.modules.get(".".join(origin[:i]))
            if mod is None:
                continue
            rest = origin[i:]
            if len(rest) == 1:
                if rest[0] in mod.classes:
                    return mod.classes[rest[0]]
                tgt = mod.imports.bindings.get(rest[0])
                if tgt and tgt != origin:
                    return self.resolve_class(tgt, depth + 1)
            return None
        return None

    def lookup_method(self, ci: ClassInfo, name: str,
                      depth: int = 0) -> FuncInfo | None:
        if name in ci.methods:
            return ci.methods[name]
        if depth > 6:
            return None
        for base in ci.bases:
            bc = self.resolve_class(base)
            if bc is not None:
                m = self.lookup_method(bc, name, depth + 1)
                if m is not None:
                    return m
        return None

    # -- per-function edge/effect collection --------------------------------
    def _collect_edges_and_effects(self, mod: ModuleInfo):
        for local, fn in mod.functions.items():
            scanner = fx.EffectScanner(mod.imports, mod.uses_jax)
            fn.sites = scanner.scan(fn.node)
            _FunctionVisitor(self, mod, fn).run()

    def _finish_roots(self):
        for fn in self.functions.values():
            if fn.local_name in fx.NAMED_ROOTS:
                self._color(fn, fx.NAMED_ROOTS[fn.local_name],
                            f"named hot path '{fn.local_name}'")
        # every call_soon-family callee runs ON the event loop
        for fn in self.functions.values():
            for e in fn.edges:
                if e.kind == "call_soon":
                    self._color(e.callee, "event-loop",
                                f"callback registered at "
                                f"{_rel(e.caller.path)}:{e.line}")
        self.roots = sorted((f for f in self.functions.values()
                             if f.root_kind), key=lambda f: f.qualname)

    # -- analysis -----------------------------------------------------------
    def findings(self) -> list["FlowFinding"]:
        """BFS each colored root per forbidden effect; one finding per
        (rule, site), keeping the shortest chain (ties: root name)."""
        best: dict[tuple, tuple] = {}  # (rule, path, line, col, detail) ->
        #                                (chain_len, root_qualname, finding)
        for root in self.roots:
            for effect in sorted(fx.ROOT_FORBIDS[root.root_kind]):
                self._bfs(root, effect, best)
        return sorted((v[2] for v in best.values()),
                      key=lambda f: (f.path, f.line, f.col, f.rule_id))

    def _bfs(self, root: FuncInfo, effect: str, best: dict):
        rule = fx.EFFECT_RULE[effect]
        parent: dict[str, tuple] = {root.qualname: None}
        queue = [root]
        while queue:
            fn = queue.pop(0)
            for site in fn.sites:
                if site.effect == effect:
                    self._emit(rule, root, fn, site, parent, best)
            for e in sorted(fn.edges,
                            key=lambda e: (e.callee.qualname, e.line)):
                if effect not in fx.EDGE_MASKS[e.kind]:
                    continue
                if e.callee.qualname in parent:
                    continue
                parent[e.callee.qualname] = (fn.qualname, e)
                queue.append(e.callee)

    def _emit(self, rule: str, root: FuncInfo, sink: FuncInfo,
              site: fx.EffectSite, parent: dict, best: dict):
        # chain: root-first hop list, each with the call site that leads in
        hops = []
        q = sink.qualname
        while q is not None:
            entry = parent[q]
            fn = self.functions[q]
            if entry is None:
                hops.append(f"{q} [{root.root_kind} root: {root.root_cause}]")
            else:
                _, e = entry
                hops.append(f"{q} [{e.kind} at {_rel(e.caller.path)}:{e.line}]")
            q = entry[0] if entry else None
        hops.reverse()
        hops.append(f"{site.detail} [{_rel(sink.path)}:{site.line}]")
        key = f"{rule}:{sink.qualname}:{site.detail}"
        n_calls = len(hops) - 2  # call hops between root and sink function
        via = (f" via {n_calls} call hop{'s' if n_calls != 1 else ''}"
               if n_calls else " directly in the root")
        f = FlowFinding(
            rule_id=rule,
            message=(f"{fx.RULE_EFFECT[rule]} effect {site.detail} reachable "
                     f"from {root.root_kind} root {root.qualname}{via}"),
            path=sink.path, line=site.line, col=site.col,
            chain=tuple(hops), key=key)
        bkey = (rule, sink.path, site.line, site.col, site.detail)
        cand = (len(hops), root.qualname, f)
        if bkey not in best or cand[:2] < best[bkey][:2]:
            best[bkey] = cand


def _rel(path: str) -> str:
    try:
        rel = os.path.relpath(path)
    except ValueError:
        return path
    return rel if not rel.startswith("..") else path


# ------------------------------------------------- per-function visitation
class _FunctionVisitor:
    """Collects call edges out of one function body. Walks statements in
    order so local forward flow (`x = ClassName(...)`, `f = jax.jit(g)`)
    is visible to later calls; skips nested def/class bodies (their own
    graph nodes) but inlines lambda bodies into the enclosing function."""

    def __init__(self, graph: CallGraph, mod: ModuleInfo, fn: FuncInfo):
        self.g = graph
        self.mod = mod
        self.fn = fn
        self.local_types: dict[str, ClassInfo] = {}
        self.local_funcs: dict[str, FuncInfo] = {}
        self.shadowed: set[str] = {
            a.arg for a in [*fn.node.args.args, *fn.node.args.kwonlyargs,
                            *fn.node.args.posonlyargs,
                            *filter(None, [fn.node.args.vararg,
                                           fn.node.args.kwarg])]}
        # nested defs are callable by bare name from the enclosing body
        nest = f"{fn.qualname.split(':', 1)[1]}.<locals>."
        for local, f2 in mod.functions.items():
            if local.startswith(nest) and "." not in local[len(nest):]:
                self.local_funcs[f2.local_name] = f2

    def run(self):
        for stmt in self.fn.node.body:
            self._walk(stmt)

    # -- traversal ----------------------------------------------------------
    def _walk(self, node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            self._track_assign(node)
        if isinstance(node, ast.Call):
            self._check_call(node)
        if isinstance(node, ast.Lambda):
            self._walk(node.body)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _track_assign(self, node: ast.Assign):
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        self.shadowed.add(name)
        self.local_types.pop(name, None)
        self.local_funcs.pop(name, None)
        v = node.value
        if isinstance(v, ast.Call):
            # x = ClassName(...): forward type flow for x.meth() edges
            ci = self._class_of_ctor(v.func)
            if ci is not None:
                self.local_types[name] = ci
                return
            # x = jax.jit(g): x() later dispatches into the jit region g
            origin = self.mod.imports.resolve(v.func)
            if origin and tuple(origin[-2:]) in _JIT_WRAPPERS and v.args:
                target = self._func_ref(v.args[0])
                if target is not None:
                    self.g._color(target, "jit-region",
                                  f"jax.jit at {_rel(self.fn.path)}:{v.lineno}")
                    self.local_funcs[name] = target
                return
        # x = self._helper / x = mod.func: callable alias
        target = self._func_ref(v)
        if target is not None:
            self.local_funcs[name] = target

    def _class_of_ctor(self, func: ast.AST) -> ClassInfo | None:
        if isinstance(func, ast.Name) and func.id in self.mod.classes \
                and func.id not in self.shadowed:
            return self.mod.classes[func.id]
        origin = self.mod.imports.resolve(func)
        if origin:
            return self.g.resolve_class(origin)
        return None

    # -- call handling ------------------------------------------------------
    def _edge(self, target: FuncInfo | None, kind: str, line: int):
        if target is not None:
            self.fn.edges.append(CallEdge(self.fn, target, kind, line))

    def _check_call(self, node: ast.Call):
        func = node.func

        if isinstance(func, ast.Attribute):
            attr = func.attr
            # asyncio callback registration edges
            if attr in _CALL_SOON and node.args:
                self._edge(self._func_ref(node.args[0]), "call_soon",
                           node.lineno)
                return
            if attr in _CALL_LATER and len(node.args) >= 2:
                self._edge(self._func_ref(node.args[1]), "call_soon",
                           node.lineno)
                return
            if attr in _TASK_CTORS and node.args:
                self._edge(self._coro_ref(node.args[0]), "task", node.lineno)
                return
            if attr == "run_in_executor" and len(node.args) >= 2:
                default = (isinstance(node.args[0], ast.Constant)
                           and node.args[0].value is None)
                self._edge(self._func_ref(node.args[1]),
                           "default-executor" if default else "executor",
                           node.lineno)
                return
            if attr == "submit" and node.args:
                target = self._func_ref(node.args[0])
                if target is not None:
                    self._edge(target, "executor", node.lineno)
                    return
            if attr == "remote":
                base = func.value
                if (isinstance(base, ast.Call)
                        and isinstance(base.func, ast.Attribute)
                        and base.func.attr == "options"):
                    base = base.func.value  # f.options(...).remote(...)
                self._edge(self._func_ref(base), "remote", node.lineno)
                # fall through: argument callbacks still scanned below

        # Thread(target=...) edges
        origin = self.mod.imports.resolve(func)
        if origin and tuple(origin[-2:]) == ("threading", "Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    self._edge(self._func_ref(kw.value), "thread",
                               node.lineno)
            return

        # traced-loop regions: lax.scan(body, ...) etc. color the body fn
        if origin and tuple(origin[-2:]) in _TRACED_LOOPS:
            idx = _TRACED_LOOPS[tuple(origin[-2:])]
            if len(node.args) > idx:
                target = self._func_ref(node.args[idx])
                if target is not None:
                    self.g._color(
                        target, "jit-region",
                        f"{'.'.join(origin[-2:])} at "
                        f"{_rel(self.fn.path)}:{node.lineno}")
            return
        if origin and tuple(origin[-2:]) in _JIT_WRAPPERS and node.args:
            # jax.jit(f)(x) or bare jax.jit(f) in expression position
            target = self._func_ref(node.args[0])
            if target is not None:
                self.g._color(target, "jit-region",
                              f"jax.jit at {_rel(self.fn.path)}:{node.lineno}")
            return

        # plain direct/method call
        self._edge(self._func_ref(func), "call", node.lineno)

    # -- reference resolution ------------------------------------------------
    def _coro_ref(self, node: ast.AST) -> FuncInfo | None:
        """create_task(coro(...)) or create_task(fn) -> fn."""
        if isinstance(node, ast.Call):
            return self._func_ref(node.func)
        return self._func_ref(node)

    def _func_ref(self, node: ast.AST) -> FuncInfo | None:
        # functools.partial(fn, ...) -> fn
        if isinstance(node, ast.Call):
            origin = self.mod.imports.resolve(node.func)
            if origin and origin[-1] == "partial" and node.args:
                return self._func_ref(node.args[0])
            return None
        if isinstance(node, ast.Name):
            if node.id in self.local_funcs:
                return self.local_funcs[node.id]
            if node.id in self.shadowed:
                return None
            origin = self.mod.imports.resolve(node)
            if origin:
                return self.g.resolve_func(origin)
            # same-module module-level function or class ctor
            fn = self.mod.functions.get(node.id)
            if fn is not None:
                return fn
            ci = self.mod.classes.get(node.id)
            if ci is not None:
                return self.g.lookup_method(ci, "__init__")
            return None
        if not isinstance(node, ast.Attribute):
            return None
        # self.meth / cls.meth
        base = node.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            ci = self._own_class()
            if ci is not None:
                return self.g.lookup_method(ci, node.attr)
            return None
        # self.attr.meth via __init__-time attribute types
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id in ("self", "cls")):
            ci = self._own_class()
            if ci is not None:
                target = ci.attr_classes.get(base.attr)
                if target is not None:
                    tc = (target if isinstance(target, ClassInfo)
                          else self.g.resolve_class(target))
                    if tc is not None:
                        return self.g.lookup_method(tc, node.attr)
            return None
        # x.meth where x = ClassName(...) locally
        if isinstance(base, ast.Name) and base.id in self.local_types:
            return self.g.lookup_method(self.local_types[base.id], node.attr)
        # ClassName.meth / module.func / pkg.mod.Class.meth
        if isinstance(base, ast.Name) and base.id in self.mod.classes \
                and base.id not in self.shadowed:
            return self.g.lookup_method(self.mod.classes[base.id], node.attr)
        origin = self.mod.imports.resolve(node)
        if origin:
            return self.g.resolve_func(origin)
        return None

    def _own_class(self) -> ClassInfo | None:
        if self.fn.class_name is None:
            return None
        return self.mod.classes.get(self.fn.class_name)


# ----------------------------------------------------------------- findings
@dataclass(frozen=True)
class FlowFinding(Finding):
    chain: tuple = ()
    key: str = ""

    def as_dict(self) -> dict:
        d = super().as_dict()
        d["chain"] = list(self.chain)  # after message: stable key order
        return d

    def render(self) -> str:
        lines = [super().render()]
        lines += [f"    {'-> ' if i else '   '}{hop}"
                  for i, hop in enumerate(self.chain)]
        return "\n".join(lines)


# ----------------------------------------------------------------- baseline
BASELINE_NAME = ".raylint_baseline.json"


def load_baseline(path: str | None) -> dict[str, str]:
    """key -> reason. Missing file with an explicit path is an error (a
    typo'd baseline silently un-suppressing nothing would green-gate);
    None means 'no baseline'."""
    if path is None:
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out = {}
    for entry in data.get("entries", []):
        out[entry["key"]] = entry.get("reason", "")
    return out


def write_baseline(path: str, findings) -> None:
    entries = [{"key": key, "reason": "baselined (pre-existing finding)"}
               for key in sorted({f.key for f in findings})]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2)
        f.write("\n")


# ------------------------------------------------------------------ driver
def analyze_paths(paths, *, baseline: str | None = None,
                  graph: CallGraph | None = None) -> list[FlowFinding]:
    """Run the interprocedural pass; returns unsuppressed findings.

    Suppression: the engine's per-line `# raylint: disable=RT02x` on the
    effect-site line (or disable-file), plus baseline keys."""
    g = graph if graph is not None else CallGraph.build(paths)
    base = load_baseline(baseline)
    kept = []
    sup_cache: dict[str, tuple] = {}
    for f in g.findings():
        if f.key in base:
            continue
        if f.path not in sup_cache:
            try:
                with open(f.path, encoding="utf-8") as fh:
                    sup_cache[f.path] = parse_suppressions(fh.read())
            except OSError:
                sup_cache[f.path] = ({}, set())
        per_line, file_wide = sup_cache[f.path]
        ids = per_line.get(f.line, set()) | file_wide  # raylint: disable=RT002 -- dict.get, not framework get()
        if f.rule_id in ids or "all" in ids:
            continue
        kept.append(f)
    return kept
